"""Sparse NDArray storage: ``RowSparseNDArray`` and ``CSRNDArray``.

TPU-native re-design of the reference sparse frontend (reference:
python/mxnet/ndarray/sparse.py; kernels in src/operator/tensor/ and
row_sparse handling in src/kvstore/kvstore_local.h).  Design mapping:

* The reference stores sparse arrays as typed Chunks with auxiliary arrays
  (indices / indptr) managed by the storage manager.  Here each sparse array
  holds its component arrays (``data``, ``indices`` [, ``indptr``]) as
  device-resident ``jax.Array`` buffers — XLA/PJRT owns allocation.
* Sparse×dense matmul lowers through ``jax.experimental.sparse.BCOO``
  (gather/scatter programs the TPU backend compiles natively) rather than
  hand-written CSR kernels.
* Data-dependent sizes (nnz) make sparse construction eager-only — the
  same restriction XLA imposes; dense fallbacks are documented per op.

Scope matches what GluonNLP-era workloads use (SURVEY §7.2 hard-part 6):
row-sparse embedding gradients, ``sparse.retain``/``row_sparse_pull`` row
gather, csr dot, elementwise add of same-stype arrays, dense conversion.
"""
from __future__ import annotations

from typing import Optional

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from .ndarray import NDArray, _wrap_out

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "zeros", "empty", "array",
           "retain", "dot", "add"]


def _jnp():
    import jax.numpy as jnp
    return jnp


_IDX_DTYPE = _np.int32  # reference uses int64; jax x64 is off → int32
                        # (documented divergence; >2^31 rows is out of scope)


class BaseSparseNDArray(NDArray):
    """Common machinery for sparse storage types.

    Subclasses carry their component buffers; the dense ``_data`` slot of
    the base class stays ``None`` — any op without a sparse implementation
    must go through ``tostype('default')`` explicitly (the reference raises
    on unsupported stype dispatch the same way).
    """

    __slots__ = ("_sp_shape", "_sp_dtype")

    def __init__(self, shape, dtype, ctx: Optional[Context] = None):
        self._data = None
        self._ctx = ctx if ctx is not None else current_context()
        self._ag_node = None
        self._ag_idx = 0
        self._require_grad = False
        self._grad = None
        self._grad_req = "null"
        self._sp_shape = tuple(int(s) for s in shape)
        self._sp_dtype = _np.dtype(dtype)

    # -- shape/dtype come from metadata, not a dense buffer ------------
    @property
    def shape(self):
        return self._sp_shape

    @property
    def dtype(self):
        return self._sp_dtype

    def _dense_jax(self):
        raise NotImplementedError

    def _components(self):
        raise NotImplementedError

    def wait_to_read(self):
        for c in self._components():
            c.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def asnumpy(self) -> _np.ndarray:
        """Dense host copy (reference: BaseSparseNDArray.asnumpy returns the
        dense materialization)."""
        return _np.asarray(self._dense_jax())

    def tostype(self, stype: str):
        if stype == self.stype:
            return self
        if stype == "default":
            return NDArray(self._dense_jax(), ctx=self._ctx)
        return _from_dense_jax(self._dense_jax(), stype, ctx=self._ctx)

    todense = lambda self: self.tostype("default")  # noqa: E731

    def astype(self, dtype, copy=True):
        if not copy and _np.dtype(dtype) == self.dtype:
            return self
        return self._astype_impl(dtype)

    # arithmetic: same-stype add/sub stay sparse; scalar mul scales data;
    # everything else densifies (reference FComputeEx fallback behavior)
    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(other, self)

    def __sub__(self, other):
        return add(self, other * -1 if isinstance(other, BaseSparseNDArray)
                   else -other)

    def __rsub__(self, other):
        return add(other, self * -1)

    def __neg__(self):
        return self * -1

    def __getitem__(self, key):
        return self.tostype("default")[key]

    def __repr__(self):
        return (f"\n<{type(self).__name__} {'x'.join(map(str, self.shape))}"
                f" @{self._ctx}>")


class RowSparseNDArray(BaseSparseNDArray):
    """A 2D+ array where only a subset of rows (leading-dim slices) are
    stored (reference: python/mxnet/ndarray/sparse.py RowSparseNDArray).

    ``indices``: sorted unique row ids, shape (nnz_rows,).
    ``data``: the stored rows, shape (nnz_rows, *shape[1:]).
    """

    __slots__ = ("_rs_data", "_rs_indices")

    def __init__(self, data, indices, shape, ctx=None, dtype=None):
        jnp = _jnp()
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        i = (indices._data if isinstance(indices, NDArray)
             else jnp.asarray(indices, _IDX_DTYPE))
        if dtype is not None:
            d = d.astype(dtype)
        super().__init__(shape, d.dtype, ctx=ctx)
        if d.ndim != len(self._sp_shape) or i.ndim != 1:
            raise MXNetError(
                f"row_sparse components malformed: data {d.shape}, "
                f"indices {i.shape} for shape {shape}")
        self._rs_data = d
        self._rs_indices = i.astype(_IDX_DTYPE)

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self) -> NDArray:
        return NDArray(self._rs_data, ctx=self._ctx)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._rs_indices, ctx=self._ctx)

    def _components(self):
        return (self._rs_data, self._rs_indices)

    def _dense_jax(self):
        jnp = _jnp()
        out = jnp.zeros(self._sp_shape, self._sp_dtype)
        if self._rs_indices.shape[0] == 0:
            return out
        return out.at[self._rs_indices].set(self._rs_data)

    def _astype_impl(self, dtype):
        return RowSparseNDArray(self._rs_data.astype(dtype),
                                self._rs_indices, self._sp_shape,
                                ctx=self._ctx)

    def _replace_with(self, other: "RowSparseNDArray"):
        """In-place component overwrite (grad-buffer deposit path)."""
        self._rs_data = other._rs_data.astype(self._sp_dtype)
        self._rs_indices = other._rs_indices
        return self

    def copy(self):
        return RowSparseNDArray(self._rs_data, self._rs_indices,
                                self._sp_shape, ctx=self._ctx)

    def __mul__(self, other):
        if _np.isscalar(other):
            return RowSparseNDArray(self._rs_data * other, self._rs_indices,
                                    self._sp_shape, ctx=self._ctx)
        return self.tostype("default") * other

    __rmul__ = __mul__

    def retain(self, row_ids) -> "RowSparseNDArray":
        return retain(self, row_ids)

    @classmethod
    def from_dense(cls, dense) -> "RowSparseNDArray":
        arr = dense.asnumpy() if isinstance(dense, NDArray) \
            else _np.asarray(dense)
        flat = arr.reshape(arr.shape[0], -1) if arr.ndim > 1 \
            else arr.reshape(-1, 1)
        rows = _np.nonzero(_np.any(flat != 0, axis=1))[0].astype(_IDX_DTYPE)
        return cls(arr[rows], rows, arr.shape,
                   ctx=dense.ctx if isinstance(dense, NDArray) else None)


class CSRNDArray(BaseSparseNDArray):
    """2D compressed-sparse-row array (reference:
    python/mxnet/ndarray/sparse.py CSRNDArray).

    ``data``: nnz values; ``indices``: nnz column ids; ``indptr``: row
    extents, shape (nrows+1,).
    """

    __slots__ = ("_cs_data", "_cs_indices", "_cs_indptr")

    def __init__(self, data, indices, indptr, shape, ctx=None, dtype=None):
        jnp = _jnp()
        d = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        i = (indices._data if isinstance(indices, NDArray)
             else jnp.asarray(indices, _IDX_DTYPE))
        p = (indptr._data if isinstance(indptr, NDArray)
             else jnp.asarray(indptr, _IDX_DTYPE))
        if dtype is not None:
            d = d.astype(dtype)
        super().__init__(shape, d.dtype, ctx=ctx)
        if len(self._sp_shape) != 2 or d.ndim != 1 or i.ndim != 1 \
                or p.shape[0] != self._sp_shape[0] + 1:
            raise MXNetError(
                f"csr components malformed: data {d.shape}, indices "
                f"{i.shape}, indptr {p.shape} for shape {shape}")
        self._cs_data = d
        self._cs_indices = i.astype(_IDX_DTYPE)
        self._cs_indptr = p.astype(_IDX_DTYPE)

    @property
    def stype(self):
        return "csr"

    @property
    def data(self) -> NDArray:
        return NDArray(self._cs_data, ctx=self._ctx)

    @property
    def indices(self) -> NDArray:
        return NDArray(self._cs_indices, ctx=self._ctx)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._cs_indptr, ctx=self._ctx)

    def _components(self):
        return (self._cs_data, self._cs_indices, self._cs_indptr)

    def _row_ids_np(self):
        ptr = _np.asarray(self._cs_indptr)
        return _np.repeat(_np.arange(len(ptr) - 1, dtype=_IDX_DTYPE),
                          _np.diff(ptr))

    def _dense_jax(self):
        jnp = _jnp()
        out = jnp.zeros(self._sp_shape, self._sp_dtype)
        if self._cs_data.shape[0] == 0:
            return out
        rows = jnp.asarray(self._row_ids_np())
        return out.at[rows, self._cs_indices].add(self._cs_data)

    def _astype_impl(self, dtype):
        return CSRNDArray(self._cs_data.astype(dtype), self._cs_indices,
                          self._cs_indptr, self._sp_shape, ctx=self._ctx)

    def _replace_with(self, other: "CSRNDArray"):
        self._cs_data = other._cs_data.astype(self._sp_dtype)
        self._cs_indices = other._cs_indices
        self._cs_indptr = other._cs_indptr
        return self

    def copy(self):
        return CSRNDArray(self._cs_data, self._cs_indices, self._cs_indptr,
                          self._sp_shape, ctx=self._ctx)

    def __mul__(self, other):
        if _np.isscalar(other):
            return CSRNDArray(self._cs_data * other, self._cs_indices,
                              self._cs_indptr, self._sp_shape, ctx=self._ctx)
        return self.tostype("default") * other

    __rmul__ = __mul__

    def _bcoo(self):
        """Lower to jax BCOO for compiled sparse matmul."""
        from jax.experimental import sparse as jsp
        jnp = _jnp()
        rows = jnp.asarray(self._row_ids_np())
        idx = jnp.stack([rows, self._cs_indices], axis=1)
        return jsp.BCOO((self._cs_data, idx), shape=self._sp_shape)

    @classmethod
    def from_dense(cls, dense) -> "CSRNDArray":
        arr = dense.asnumpy() if isinstance(dense, NDArray) \
            else _np.asarray(dense)
        if arr.ndim != 2:
            raise MXNetError("csr requires a 2D array")
        rows, cols = _np.nonzero(arr)
        order = _np.lexsort((cols, rows))
        rows, cols = rows[order], cols[order]
        indptr = _np.zeros(arr.shape[0] + 1, dtype=_IDX_DTYPE)
        _np.add.at(indptr, rows + 1, 1)
        indptr = _np.cumsum(indptr).astype(_IDX_DTYPE)
        return cls(arr[rows, cols], cols.astype(_IDX_DTYPE), indptr,
                   arr.shape,
                   ctx=dense.ctx if isinstance(dense, NDArray) else None)


# ---------------------------------------------------------------------------
# constructors (reference: sparse.py csr_matrix / row_sparse_array / zeros)
# ---------------------------------------------------------------------------
def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray from (data, indices) or a dense source
    (reference: sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        if shape is None:
            raise MXNetError("shape is required with (data, indices)")
        return RowSparseNDArray(data, indices, shape, ctx=ctx, dtype=dtype)
    if isinstance(arg1, RowSparseNDArray):
        return arg1.copy()
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    if dtype is not None:
        src = src.astype(dtype)
    return RowSparseNDArray.from_dense(src)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray from (data, indices, indptr) / (data, (row, col))
    / dense (reference: sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("shape is required with (data, indices, indptr)")
        return CSRNDArray(data, indices, indptr, shape, ctx=ctx, dtype=dtype)
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, rowcol = arg1
        if not (isinstance(rowcol, (tuple, list)) and len(rowcol) == 2):
            raise MXNetError(
                "csr_matrix with a 2-tuple expects (data, (row, col)); "
                "use (data, indices, indptr) for CSR components")
        row, col = rowcol
        if shape is None:
            raise MXNetError("shape is required with (data, (row, col))")
        dense = _np.zeros(shape, _np.asarray(data).dtype)
        _np.add.at(dense, (_np.asarray(row), _np.asarray(col)),
                   _np.asarray(data))
        return CSRNDArray.from_dense(dense.astype(dtype) if dtype else dense)
    if isinstance(arg1, CSRNDArray):
        return arg1.copy()
    src = arg1.asnumpy() if isinstance(arg1, NDArray) else _np.asarray(arg1)
    if dtype is not None:
        src = src.astype(dtype)
    return CSRNDArray.from_dense(src)


def zeros(stype, shape, ctx=None, dtype=None):
    """All-zero sparse array: empty component buffers (reference:
    sparse.zeros)."""
    jnp = _jnp()
    dtype = dtype or _np.float32
    if isinstance(shape, int):
        shape = (shape,)
    if stype == "row_sparse":
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype),
            jnp.zeros((0,), _IDX_DTYPE), shape, ctx=ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((0,), _IDX_DTYPE),
                          jnp.zeros((shape[0] + 1,), _IDX_DTYPE), shape,
                          ctx=ctx)
    if stype == "default":
        from . import ndarray as _ndmod
        return _ndmod.zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown stype {stype!r}")


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    """Sparse-preserving array() (reference: sparse.array)."""
    if isinstance(source_array, BaseSparseNDArray):
        out = source_array.copy()
        if dtype is not None:
            out = out.astype(dtype)
        return out
    raise MXNetError("sparse.array expects a sparse source; use "
                     "csr_matrix/row_sparse_array for dense sources")


# ---------------------------------------------------------------------------
# ops (reference: src/operator/tensor sparse FComputeEx kernels)
# ---------------------------------------------------------------------------
def retain(data: RowSparseNDArray, indices) -> RowSparseNDArray:
    """Keep only the requested rows (reference: sparse_retain op) — the
    primitive under row_sparse_pull."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    want = _np.asarray(indices.asnumpy() if isinstance(indices, NDArray)
                       else indices).astype(_np.int64).reshape(-1)
    have = _np.asarray(data._rs_indices)
    keep = _np.nonzero(_np.isin(have, want))[0]
    jnp = _jnp()
    return RowSparseNDArray(data._rs_data[jnp.asarray(keep)],
                            have[keep].astype(_IDX_DTYPE), data.shape,
                            ctx=data._ctx)


def _merge_row_sparse(a: RowSparseNDArray,
                      b: RowSparseNDArray) -> RowSparseNDArray:
    """Row-union sum of two row_sparse arrays (gradient accumulation)."""
    jnp = _jnp()
    ia, ib = _np.asarray(a._rs_indices), _np.asarray(b._rs_indices)
    rows, inv = _np.unique(_np.concatenate([ia, ib]), return_inverse=True)
    import jax
    data = jax.ops.segment_sum(
        jnp.concatenate([a._rs_data, b._rs_data], axis=0),
        jnp.asarray(inv.astype(_IDX_DTYPE)), num_segments=len(rows))
    return RowSparseNDArray(data, rows.astype(_IDX_DTYPE), a.shape,
                            ctx=a._ctx)


def add(lhs, rhs):
    """Elementwise add with stype dispatch: same-stype stays sparse
    (reference: elemwise_add FComputeEx); mixed densifies."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError(f"shape mismatch {lhs.shape} vs {rhs.shape}")
        return _merge_row_sparse(lhs, rhs)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError(f"shape mismatch {lhs.shape} vs {rhs.shape}")
        return CSRNDArray.from_dense(lhs._dense_jax() + rhs._dense_jax())
    a = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return a + b


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference: dot FComputeEx for csr):
    dot(csr, dense) and dot(csr.T, dense) lower through BCOO so XLA compiles
    the gather/scatter; other combinations densify."""
    from . import ops as _ops
    from .ndarray import _invoke
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs,
                                                      BaseSparseNDArray):
        import jax
        mat = lhs._bcoo()
        if transpose_a:
            mat = mat.T
        rhs_nd = rhs if isinstance(rhs, NDArray) \
            else NDArray(_jnp().asarray(rhs), ctx=lhs._ctx)

        from .. import autograd as _ag_mod
        rhs_active = (_ag_mod.is_recording()
                      and rhs_nd._tape_entry_active()
                      and not isinstance(rhs_nd._data, jax.core.Tracer))
        if rhs_active and not transpose_b:
            # custom tape node with a DIRECTLY-sparse cotangent: only the
            # rows of rhs the csr structure touches are materialized
            # (never a dense (dim, k) buffer — the reference's
            # dot(csr, dense) backward is likewise row_sparse,
            # src/operator/tensor/dot-inl.h DotCsrDenseGrad)
            jnp = _jnp()
            out = NDArray(mat @ rhs_nd._data, ctx=lhs._ctx)
            vals = lhs._cs_data
            cols = lhs._cs_indices
            indptr = lhs._cs_indptr
            m = lhs.shape[0]
            row_of_nnz = jnp.repeat(
                jnp.arange(m), jnp.diff(indptr),
                total_repeat_length=cols.shape[0])
            wshape, wctx = rhs_nd.shape, rhs_nd.ctx

            def sparse_vjp(cot):
                # grad[j] = sum over nnz (i, j, v) of v * cot[i]   (no
                # transpose_a);  transpose_a: grad[i] += v * cot[j]
                tgt = cols if not transpose_a else row_of_nnz
                src = row_of_nnz if not transpose_a else cols
                rows_np = _np.unique(_np.asarray(tgt))
                seg = _np.searchsorted(rows_np, _np.asarray(tgt))
                contrib = vals[:, None] * cot[src]
                data = jax.ops.segment_sum(
                    contrib, jnp.asarray(seg), num_segments=len(rows_np))
                return (RowSparseNDArray(data, rows_np, wshape,
                                         ctx=wctx),)

            node = _ag_mod._TapeNode(fun=None, inputs=[rhs_nd],
                                     vjp_fn=sparse_vjp,
                                     out_is_tuple=False,
                                     name="sparse_dot(row_sparse_grad)",
                                     custom=True)
            node.out_avals = [(out.shape, out.dtype)]
            out._ag_node = node
            out._ag_idx = 0
            return out

        # fallback (not recording / transpose_b / under trace): route
        # through _invoke so the tape records with a dense vjp
        def fn(r):
            return mat @ (r.T if transpose_b else r)
        out = _invoke(fn, [rhs_nd], name="sparse_dot")
        out._ctx = lhs._ctx          # placement follows the csr operand
        return out
    a = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    return _ops.dot(a, b, transpose_a=transpose_a, transpose_b=transpose_b)


def _from_dense_jax(jarr, stype, ctx=None):
    if stype == "row_sparse":
        return RowSparseNDArray.from_dense(NDArray(jarr, ctx=ctx))
    if stype == "csr":
        return CSRNDArray.from_dense(NDArray(jarr, ctx=ctx))
    raise MXNetError(f"unknown stype {stype!r}")


def embedding_row_sparse_grad(idx_np: _np.ndarray, cotangent,
                              weight_shape, ctx=None) -> RowSparseNDArray:
    """Build the row_sparse gradient of an Embedding lookup: unique touched
    rows + segment-summed cotangent slices (reference: indexing_op.cc
    EmbeddingOpBackward with row_sparse output; SURVEY §7.2 hard-part 6)."""
    import jax
    jnp = _jnp()
    flat_idx = _np.asarray(idx_np).astype(_np.int64).reshape(-1)
    rows, inv = _np.unique(flat_idx, return_inverse=True)
    cot = cotangent.reshape((-1,) + tuple(weight_shape[1:]))
    data = jax.ops.segment_sum(cot, jnp.asarray(inv.astype(_IDX_DTYPE)),
                               num_segments=len(rows))
    return RowSparseNDArray(data, rows.astype(_IDX_DTYPE), weight_shape,
                            ctx=ctx)
