"""``mx.nd.contrib`` — detection ops, control flow, and misc extensions
(reference: src/operator/contrib/*: multibox_prior.cc, multibox_target.cc,
multibox_detection.cc, bounding_box.cc (box_nms/box_iou), roi_align.cc,
bilinear_resize.cc, adaptive_avg_pooling.cc; control flow:
src/operator/control_flow.cc with python sugar in
python/mxnet/ndarray/contrib.py).

TPU-first re-design notes:
  * Data-dependent result sizes (NMS, matching) use the fixed-size +
    valid-marker pattern the reference also uses (-1-filled rows), so every
    kernel is static-shape and jit/vmap-able — nothing here blocks XLA.
  * NMS is the O(n²) IoU-matrix + lax.scan suppression sweep: a (topk,topk)
    matrix fits VMEM for typical anchor counts and maps to the MXU, instead
    of the reference's serialized CUDA bitonic+bitmask kernels.
  * AdaptiveAvgPooling2D is lowered to two small matmuls (precomputed
    row/col averaging weights), which beats gather-based pooling on TPU.
  * foreach lowers to lax.scan (compiled loop, grad via scan's VJP).
    while_loop/cond execute eagerly when values are concrete (the
    reference's imperative path), and lower to lax.while_loop/lax.cond
    when tracing (hybridize/jit) — one compiled program, matching the
    reference's control_flow.cc subgraph ops inside the graph executor.
    Traced while_loop is forward-only for autodiff (lax.while_loop has no
    reverse-mode rule); use foreach for differentiable loops.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _invoke

__all__ = ["box_iou", "box_nms", "bipartite_matching", "MultiBoxPrior",
           "MultiBoxTarget", "MultiBoxDetection", "ROIAlign", "Proposal",
           "BilinearResize2D", "AdaptiveAvgPooling2D", "foreach",
           "while_loop", "cond", "isinf", "isnan", "isfinite",
           "arange_like", "index_array", "index_copy", "boolean_mask",
           "quadratic", "getnnz", "allclose", "CTCLoss", "ctc_loss",
           "fft", "ifft", "interleaved_matmul_selfatt_qk",
           "interleaved_matmul_selfatt_valatt", "count_sketch",
           "PSROIPooling", "psroipooling"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _corner(box):
    # (..., 4) xmin,ymin,xmax,ymax
    return box[..., 0], box[..., 1], box[..., 2], box[..., 3]


def _iou_corner(a, b):
    """IoU between (..., Na, 4) and (..., Nb, 4) corner boxes → (..., Na, Nb)."""
    jnp = _jnp()
    ax0, ay0, ax1, ay1 = [t[..., :, None] for t in _corner(a)]
    bx0, by0, bx1, by1 = [t[..., None, :] for t in _corner(b)]
    iw = jnp.clip(jnp.minimum(ax1, bx1) - jnp.maximum(ax0, bx0), 0, None)
    ih = jnp.clip(jnp.minimum(ay1, by1) - jnp.maximum(ay0, by0), 0, None)
    inter = iw * ih
    area_a = jnp.clip(ax1 - ax0, 0, None) * jnp.clip(ay1 - ay0, 0, None)
    area_b = jnp.clip(bx1 - bx0, 0, None) * jnp.clip(by1 - by0, 0, None)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _to_corner(box, fmt):
    jnp = _jnp()
    if fmt == "corner":
        return box
    cx, cy, w, h = _corner(box)
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: bounding_box.cc _contrib_box_iou)."""
    fmt = format

    def run(a, b):
        return _iou_corner(_to_corner(a, fmt), _to_corner(b, fmt))
    return _invoke(run, [lhs, rhs], name="box_iou")


def box_nms(data, overlap_thresh=0.5, valid_thresh=0, topk=-1, coord_start=2,
            score_index=1, id_index=-1, background_id=-1, force_suppress=False,
            in_format="corner", out_format="corner"):
    """Non-maximum suppression (reference: bounding_box.cc
    _contrib_box_nms).  Input (..., N, K) rows [id?, score, x0,y0,x1,y1,...];
    suppressed rows are -1-filled, shape is preserved (fixed-size pattern).
    """
    def run(x):
        import jax
        jnp = _jnp()
        lax = jax.lax
        batch_shape = x.shape[:-2]
        N, K = x.shape[-2], x.shape[-1]
        flat = x.reshape((-1, N, K))

        def one(sample):
            score = sample[:, score_index]
            valid = score > valid_thresh
            if id_index >= 0 and background_id >= 0:
                valid &= sample[:, id_index] != background_id
            order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
            s = sample[order]
            svalid = valid[order]
            if topk > 0:
                svalid &= jnp.arange(N) < topk
            boxes = _to_corner(s[:, coord_start:coord_start + 4], in_format)
            iou = _iou_corner(boxes, boxes)
            if id_index >= 0 and not force_suppress:
                same = s[:, id_index][:, None] == s[:, id_index][None, :]
                iou = jnp.where(same, iou, 0.0)

            # sequential sweep in score order: i survives unless some
            # earlier survivor overlaps it
            def step(kept, i):
                over = (iou[i] > overlap_thresh) & kept
                over = over & (jnp.arange(N) < i)
                keep_i = svalid[i] & ~over.any()
                kept = kept.at[i].set(keep_i)
                return kept, keep_i

            kept, _ = lax.scan(step, jnp.zeros((N,), bool), jnp.arange(N))
            out = jnp.where(kept[:, None], s, -jnp.ones_like(s))
            if out_format != in_format:
                coords = out[:, coord_start:coord_start + 4]
                conv = (_to_corner(coords, in_format) if out_format == "corner"
                        else _from_corner(coords))
                out = out.at[:, coord_start:coord_start + 4].set(
                    jnp.where(kept[:, None], conv, -1.0))
            return out

        out = jax.vmap(one)(flat)
        return out.reshape(batch_shape + (N, K))
    return _invoke(run, [data], name="box_nms")


def _from_corner(box):
    jnp = _jnp()
    x0, y0, x1, y1 = _corner(box)
    return jnp.stack([(x0 + x1) / 2, (y0 + y1) / 2, x1 - x0, y1 - y0],
                     axis=-1)


def bipartite_matching(data, threshold, is_ascend=False, topk=-1):
    """Greedy bipartite matching over a score matrix (reference:
    bounding_box.cc _contrib_bipartite_matching).  Returns (row_match,
    col_match): for each row the matched col (or -1), and inverse."""
    def run(x):
        import jax
        jnp = _jnp()
        lax = jax.lax
        batch = x.shape[:-2]
        R, C = x.shape[-2:]
        flat = x.reshape((-1, R, C))
        sign = 1.0 if is_ascend else -1.0
        n_iter = R if topk <= 0 else min(topk, R)

        def one(score):
            s = sign * score  # minimize s

            def step(carry, _):
                s_cur, row_m, col_m = carry
                idx = jnp.argmin(s_cur)
                r, c = idx // C, idx % C
                ok = (s_cur[r, c] <= sign * threshold
                      if is_ascend else s_cur[r, c] < -threshold)
                row_m = jnp.where(ok, row_m.at[r].set(c), row_m)
                col_m = jnp.where(ok, col_m.at[c].set(r), col_m)
                s_cur = jnp.where(ok, s_cur.at[r, :].set(jnp.inf), s_cur)
                s_cur = jnp.where(ok, s_cur.at[:, c].set(jnp.inf), s_cur)
                return (s_cur, row_m, col_m), None

            init = (s, -jnp.ones((R,), jnp.float32),
                    -jnp.ones((C,), jnp.float32))
            (_, row_m, col_m), _ = lax.scan(step, init, None, length=n_iter)
            return row_m, col_m

        rows, cols = jax.vmap(one)(flat)
        return rows.reshape(batch + (R,)), cols.reshape(batch + (C,))

    out = _invoke(run, [data], name="bipartite_matching",
                  differentiable=False)
    return out


def MultiBoxPrior(data, sizes=(1.0,), ratios=(1.0,), clip=False, steps=(-1.0, -1.0),
                  offsets=(0.5, 0.5)):
    """Anchor-box generation (reference: multibox_prior.cc).  data: (B,C,H,W)
    → (1, H*W*(len(sizes)+len(ratios)-1), 4) corner boxes."""
    sizes = tuple(float(s) for s in sizes)
    ratios = tuple(float(r) for r in ratios)

    def run(x):
        jnp = _jnp()
        H, W = x.shape[2], x.shape[3]
        step_y = steps[0] if steps[0] > 0 else 1.0 / H
        step_x = steps[1] if steps[1] > 0 else 1.0 / W
        cy = (jnp.arange(H) + offsets[0]) * step_y
        cx = (jnp.arange(W) + offsets[1]) * step_x
        cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"), -1)  # H,W,2
        wh = []
        for s in sizes:
            wh.append((s * _np.sqrt(ratios[0]), s / _np.sqrt(ratios[0])))
        for r in ratios[1:]:
            wh.append((sizes[0] * _np.sqrt(r), sizes[0] / _np.sqrt(r)))
        wh = jnp.asarray(wh)  # A,2 (w,h)
        A = wh.shape[0]
        ctr = jnp.broadcast_to(cyx[:, :, None, :], (H, W, A, 2))
        half_w = wh[None, None, :, 0] / 2
        half_h = wh[None, None, :, 1] / 2
        x0 = ctr[..., 1] - half_w
        y0 = ctr[..., 0] - half_h
        x1 = ctr[..., 1] + half_w
        y1 = ctr[..., 0] + half_h
        out = jnp.stack([x0, y0, x1, y1], -1).reshape(1, H * W * A, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out.astype(x.dtype)
    return _invoke(run, [data], name="MultiBoxPrior", differentiable=False)


def MultiBoxTarget(anchor, label, cls_pred, overlap_threshold=0.5,
                   ignore_label=-1.0, negative_mining_ratio=-1.0,
                   negative_mining_thresh=0.5, minimum_negative_samples=0,
                   variances=(0.1, 0.1, 0.2, 0.2)):
    """Assign ground-truth to anchors + encode regression targets
    (reference: multibox_target.cc).  anchor (1,N,4) corner; label
    (B,M,5) [cls,x0,y0,x1,y1] (-1 rows pad); cls_pred (B,num_cls+1,N).
    Returns [loc_target (B,N*4), loc_mask (B,N*4), cls_target (B,N)].

    With ``negative_mining_ratio > 0``, hard-negative mining keeps the
    ``ratio × num_pos`` highest-confidence negatives (max non-background
    score in ``cls_pred``) below ``negative_mining_thresh`` IoU; unmined
    negatives get ``ignore_label``."""
    var = tuple(float(v) for v in variances)

    def run(anc, lab, pred):
        import jax
        jnp = _jnp()
        ancs = anc.reshape(-1, 4)                     # N,4
        N = ancs.shape[0]

        def one(lb, cf):
            gt_valid = lb[:, 0] >= 0                  # M
            gt_boxes = lb[:, 1:5]                     # M,4
            iou = _iou_corner(ancs, gt_boxes)         # N,M
            iou = jnp.where(gt_valid[None, :], iou, -1.0)
            best_gt = jnp.argmax(iou, 1)              # N
            best_iou = jnp.take_along_axis(iou, best_gt[:, None], 1)[:, 0]
            # forced gt->anchor assignment via iterative greedy bipartite
            # matching: each round claims the globally-best (anchor, gt)
            # pair among still-unmatched rows/cols, so two gts sharing a
            # best anchor get distinct anchors (the loser takes its
            # next-best) instead of overwriting each other
            M = lb.shape[0]

            def bip_round(carry, _):
                forced_gt, forced, gt_done = carry
                masked = jnp.where(
                    forced[:, None] | gt_done[None, :]
                    | ~gt_valid[None, :], -1.0, iou)
                flat = jnp.argmax(masked)
                a_i, g_i = flat // M, flat % M
                ok = masked.reshape(-1)[flat] > 0
                forced = forced.at[a_i].set(ok | forced[a_i])
                gt_done = gt_done.at[g_i].set(ok | gt_done[g_i])
                forced_gt = forced_gt.at[a_i].set(
                    jnp.where(ok, g_i.astype(jnp.int32), forced_gt[a_i]))
                return (forced_gt, forced, gt_done), None

            (forced_gt, forced, _), _ = jax.lax.scan(
                bip_round,
                (jnp.zeros((N,), jnp.int32), jnp.zeros((N,), bool),
                 jnp.zeros((M,), bool)),
                None, length=M)
            pos = forced | (best_iou >= overlap_threshold)
            gt_idx = jnp.where(forced, forced_gt, best_gt)
            matched = gt_boxes[gt_idx]                # N,4
            # encode center-size offsets scaled by variances
            acx, acy = (ancs[:, 0] + ancs[:, 2]) / 2, (ancs[:, 1] + ancs[:, 3]) / 2
            aw = jnp.clip(ancs[:, 2] - ancs[:, 0], 1e-8, None)
            ah = jnp.clip(ancs[:, 3] - ancs[:, 1], 1e-8, None)
            gcx, gcy = (matched[:, 0] + matched[:, 2]) / 2, (matched[:, 1] + matched[:, 3]) / 2
            gw = jnp.clip(matched[:, 2] - matched[:, 0], 1e-8, None)
            gh = jnp.clip(matched[:, 3] - matched[:, 1], 1e-8, None)
            tx = (gcx - acx) / aw / var[0]
            ty = (gcy - acy) / ah / var[1]
            tw = jnp.log(gw / aw) / var[2]
            th = jnp.log(gh / ah) / var[3]
            loc_t = jnp.stack([tx, ty, tw, th], 1)    # N,4
            loc_t = jnp.where(pos[:, None], loc_t, 0.0).reshape(-1)
            loc_m = jnp.where(pos[:, None],
                              jnp.ones((N, 4)), 0.0).reshape(-1)
            if negative_mining_ratio > 0:
                neg_cand = ~pos & (best_iou < negative_mining_thresh)
                hard = jnp.max(cf[1:], axis=0)        # max fg confidence
                hard = jnp.where(neg_cand, hard, -jnp.inf)
                k = jnp.maximum(
                    (negative_mining_ratio
                     * pos.sum()).astype(jnp.int32),
                    minimum_negative_samples)
                order = jnp.argsort(-hard)
                rank = jnp.zeros((N,), jnp.int32).at[order].set(
                    jnp.arange(N, dtype=jnp.int32))
                mined = neg_cand & (rank < k)
                cls_t = jnp.where(
                    pos, lb[gt_idx, 0] + 1.0,
                    jnp.where(mined, 0.0, ignore_label))
            else:
                cls_t = jnp.where(pos, lb[gt_idx, 0] + 1.0, 0.0)
            return loc_t, loc_m, cls_t

        loc_t, loc_m, cls_t = jax.vmap(one)(lab, pred)
        return (loc_t.astype(anc.dtype), loc_m.astype(anc.dtype),
                cls_t.astype(anc.dtype))
    return _invoke(run, [anchor, label, cls_pred], name="MultiBoxTarget",
                   differentiable=False)


def MultiBoxDetection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                      background_id=0, nms_threshold=0.5, force_suppress=False,
                      variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions to detections + per-class NMS (reference:
    multibox_detection.cc).  cls_prob (B,C,N), loc_pred (B,N*4), anchor
    (1,N,4) → (B,N,6) rows [cls_id, score, x0,y0,x1,y1], -1 = invalid."""
    var = tuple(float(v) for v in variances)

    def run(prob, loc, anc):
        jnp = _jnp()
        B, C, N = prob.shape
        ancs = anc.reshape(-1, 4)
        acx, acy = (ancs[:, 0] + ancs[:, 2]) / 2, (ancs[:, 1] + ancs[:, 3]) / 2
        aw = ancs[:, 2] - ancs[:, 0]
        ah = ancs[:, 3] - ancs[:, 1]
        l = loc.reshape(B, N, 4)
        cx = l[..., 0] * var[0] * aw + acx
        cy = l[..., 1] * var[1] * ah + acy
        w = jnp.exp(l[..., 2] * var[2]) * aw
        h = jnp.exp(l[..., 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                          -1)                          # B,N,4
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        fg = jnp.concatenate([prob[:, :background_id],
                              prob[:, background_id + 1:]], 1)  # B,C-1,N
        cls_id = jnp.argmax(fg, 1).astype(prob.dtype)           # B,N
        score = jnp.max(fg, 1)
        keep = score > threshold
        det = jnp.concatenate(
            [jnp.where(keep, cls_id, -1.0)[..., None],
             jnp.where(keep, score, -1.0)[..., None],
             jnp.where(keep[..., None], boxes, -1.0)], -1)      # B,N,6
        return det
    det = _invoke(run, [cls_prob, loc_pred, anchor],
                  name="MultiBoxDetection", differentiable=False)
    return box_nms(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                   topk=nms_topk, coord_start=2, score_index=1, id_index=0,
                   background_id=-1, force_suppress=force_suppress)


def ROIAlign(data, rois, pooled_size, spatial_scale, sample_ratio=-1,
             position_sensitive=False, aligned=False):
    """ROI Align with bilinear sampling (reference: roi_align.cc).  data
    (B,C,H,W); rois (R,5) [batch_idx,x0,y0,x1,y1] in image coords."""
    ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
              else (pooled_size, pooled_size))
    if position_sensitive:
        raise MXNetError("ROIAlign(position_sensitive=True) (PS-ROIAlign) "
                         "is not implemented in this build")

    def run(x, r):
        import jax
        jnp = _jnp()
        B, C, H, W = x.shape
        offset = 0.5 if aligned else 0.0

        def one_roi(roi):
            bidx = roi[0].astype(jnp.int32)
            x0 = roi[1] * spatial_scale - offset
            y0 = roi[2] * spatial_scale - offset
            x1 = roi[3] * spatial_scale - offset
            y1 = roi[4] * spatial_scale - offset
            rw = jnp.maximum(x1 - x0, 1.0 if not aligned else 1e-6)
            rh = jnp.maximum(y1 - y0, 1.0 if not aligned else 1e-6)
            ns = sample_ratio if sample_ratio > 0 else 2
            # sample grid: (ph*ns, pw*ns)
            ys = y0 + (jnp.arange(ph * ns) + 0.5) * rh / (ph * ns)
            xs = x0 + (jnp.arange(pw * ns) + 0.5) * rw / (pw * ns)
            img = x[bidx]                              # C,H,W

            def bilinear(c_img):
                yy = jnp.clip(ys, 0, H - 1)
                xx = jnp.clip(xs, 0, W - 1)
                y0i = jnp.floor(yy).astype(jnp.int32)
                x0i = jnp.floor(xx).astype(jnp.int32)
                y1i = jnp.minimum(y0i + 1, H - 1)
                x1i = jnp.minimum(x0i + 1, W - 1)
                wy = (yy - y0i)[:, None]
                wx = (xx - x0i)[None, :]
                v00 = c_img[y0i][:, x0i]
                v01 = c_img[y0i][:, x1i]
                v10 = c_img[y1i][:, x0i]
                v11 = c_img[y1i][:, x1i]
                val = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                       + v10 * wy * (1 - wx) + v11 * wy * wx)
                return val.reshape(ph, ns, pw, ns).mean((1, 3))

            return jax.vmap(bilinear)(img)             # C,ph,pw

        return jax.vmap(one_roi)(r)                    # R,C,ph,pw
    return _invoke(run, [data, rois], name="ROIAlign")


_BRS2D_MODES = ("size", "odd_scale", "like", "to_even_down", "to_even_up",
                "to_odd_down", "to_odd_up")


def BilinearResize2D(data, like=None, height=None, width=None,
                     scale_height=None, scale_width=None, mode="size",
                     align_corners=True):
    """Bilinear resize (reference: src/operator/contrib/
    bilinear_resize.cc).  ``mode`` selects how the output size derives
    from the input's (H, W):

    * ``size``        — explicit ``height``/``width`` (or scale factors);
    * ``odd_scale``   — scale then force odd: even dims give
      ``d*scale + 1``, odd dims ``(d-1)*scale + 1``;
    * ``like``        — match the spatial size of the second input;
    * ``to_even_down``/``to_even_up``/``to_odd_down``/``to_odd_up`` —
      nearest even/odd dimension below/above (no scaling).
    """
    if mode not in _BRS2D_MODES:
        raise MXNetError(f"BilinearResize2D: unknown mode={mode!r} "
                         f"(choose from {_BRS2D_MODES})")
    if mode == "like" and like is None:
        raise MXNetError("BilinearResize2D: mode='like' needs a second "
                         "input to take the target size from")
    if mode == "odd_scale" and not (scale_height and scale_width):
        raise MXNetError("BilinearResize2D: mode='odd_scale' needs "
                         "scale_height and scale_width")

    def _target(H, W, like_shape):
        if mode == "size":
            h = int(height) if height \
                else int(round(H * (scale_height or 1)))
            w = int(width) if width \
                else int(round(W * (scale_width or 1)))
        elif mode == "odd_scale":
            h = (int(H * scale_height) + 1 if H % 2 == 0
                 else int((H - 1) * scale_height) + 1)
            w = (int(W * scale_width) + 1 if W % 2 == 0
                 else int((W - 1) * scale_width) + 1)
        elif mode == "like":
            h, w = int(like_shape[2]), int(like_shape[3])
        elif mode == "to_even_down":
            h, w = H - (H % 2), W - (W % 2)
        elif mode == "to_even_up":
            h, w = H + (H % 2), W + (W % 2)
        elif mode == "to_odd_down":
            h, w = H - 1 + (H % 2), W - 1 + (W % 2)
        else:                        # to_odd_up
            h, w = H + 1 - (H % 2), W + 1 - (W % 2)
        return max(h, 1), max(w, 1)

    like_shape = tuple(like.shape) if like is not None else None

    def run(x):
        import jax
        jnp = _jnp()
        B, C, H, W = x.shape
        h, w = _target(H, W, like_shape)
        if align_corners and h > 1 and w > 1:
            ys = jnp.linspace(0, H - 1, h)
            xs = jnp.linspace(0, W - 1, w)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, H - 1)
            x1 = jnp.minimum(x0 + 1, W - 1)
            wy = (ys - y0)[:, None]
            wx = (xs - x0)[None, :]
            v00 = x[:, :, y0][:, :, :, x0]
            v01 = x[:, :, y0][:, :, :, x1]
            v10 = x[:, :, y1][:, :, :, x0]
            v11 = x[:, :, y1][:, :, :, x1]
            return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
                    + v10 * wy * (1 - wx) + v11 * wy * wx)
        return jax.image.resize(x, (B, C, h, w), method="bilinear")
    return _invoke(run, [data], name="BilinearResize2D")


def AdaptiveAvgPooling2D(data, output_size=1):
    """Adaptive average pooling (reference: adaptive_avg_pooling.cc).

    Lowered to two matmuls with precomputed averaging weights
    (out = Wh · x · Wwᵀ) — MXU-friendly, no gathers."""
    oh, ow = (output_size if isinstance(output_size, (tuple, list))
              else (output_size, output_size))

    def _weights(in_dim, out_dim):
        w = _np.zeros((out_dim, in_dim), dtype=_np.float32)
        for i in range(out_dim):
            start = int(_np.floor(i * in_dim / out_dim))
            end = int(_np.ceil((i + 1) * in_dim / out_dim))
            w[i, start:end] = 1.0 / (end - start)
        return w

    def run(x):
        jnp = _jnp()
        H, W = x.shape[2], x.shape[3]
        wh = jnp.asarray(_weights(H, oh), dtype=x.dtype)
        ww = jnp.asarray(_weights(W, ow), dtype=x.dtype)
        return jnp.einsum("oh,bchw,pw->bcop", wh, x, ww)
    return _invoke(run, [data], name="AdaptiveAvgPooling2D")


# ---------------------------------------------------------------------------
# control flow (reference: src/operator/control_flow.cc foreach/while_loop/
# cond subgraph ops; python sugar python/mxnet/ndarray/contrib.py)
# ---------------------------------------------------------------------------
def foreach(body, data, init_states):
    """Scan ``body`` over axis 0 of ``data`` (reference: contrib.foreach).

    body(item, states) -> (output, new_states).  Compiled to a single
    ``lax.scan`` — one XLA loop, differentiable, no per-step dispatch.
    """
    single_data = isinstance(data, NDArray)
    data_list = [data] if single_data else list(data)
    single_state = isinstance(init_states, NDArray)
    states_list = [init_states] if single_state else list(init_states)
    n_data = len(data_list)

    def run(*jarrs):
        import jax
        d = jarrs[:n_data]
        s = list(jarrs[n_data:])

        def step(carry, xs):
            xs_nd = [NDArray(x) for x in (xs if n_data > 1 else [xs])]
            st_nd = [NDArray(c) for c in carry]
            out, new_states = body(xs_nd[0] if single_data else xs_nd,
                                   st_nd[0] if single_state else st_nd)
            out_j = (out._data if isinstance(out, NDArray)
                     else [o._data for o in out])
            ns = ([new_states._data] if isinstance(new_states, NDArray)
                  else [o._data for o in new_states])
            return ns, out_j

        final, outs = jax.lax.scan(step, list(s),
                                   d[0] if n_data == 1 else tuple(d))
        if isinstance(outs, (tuple, list)):
            return tuple(outs) + tuple(final)
        return (outs,) + tuple(final)

    res = _invoke(run, data_list + states_list, name="foreach")
    res = res if isinstance(res, list) else [res]
    n_states = len(states_list)
    n_out = len(res) - n_states
    outs = res[:n_out]
    states = res[n_out:]
    return (outs[0] if len(outs) == 1 else outs,
            states[0] if single_state else states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Imperative while loop (reference: contrib.while_loop).  The trip
    count is data-dependent, so this runs eagerly — each iteration's body
    is still jit-compiled op-by-op.  Returns (outputs_stacked, loop_vars).

    Reference contract kept: ``max_iterations`` is required (ValueError
    otherwise) and stacked outputs have leading dimension
    ``max_iterations`` — steps beyond the actual trip count are
    zero-padded — so code ported from the reference sees identical
    shapes.  One documented deviation: if the condition is false on
    entry, the body never ran, eager mode cannot know the output shapes,
    and ``outputs`` is an empty list (the reference's symbolic op reads
    shapes from the graph; running the body speculatively to discover
    them would execute user side effects a zero-trip loop must not
    have)."""
    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations "
                         "(reference: contrib.while_loop)")
    max_iterations = int(max_iterations)
    if max_iterations < 0:
        raise ValueError("max_iterations must be non-negative")
    single = isinstance(loop_vars, NDArray)
    lv = [loop_vars] if single else list(loop_vars)
    import jax
    if any(isinstance(v._data, jax.core.Tracer) for v in lv):
        return _while_loop_traced(cond, func, lv, single, max_iterations)
    outputs = []
    it = 0
    while it < max_iterations and bool(cond(*lv).asnumpy()):
        out, lv_new = func(*lv)
        lv = [lv_new] if isinstance(lv_new, NDArray) else list(lv_new)
        outputs.append([out] if isinstance(out, NDArray) else list(out))
        it += 1
    from . import ops as _ops
    from .ndarray import zeros as _zeros
    if outputs:
        n_out = len(outputs[0])
        stacked = []
        for i in range(n_out):
            s = _ops.stack(*[o[i] for o in outputs], axis=0)
            if it < max_iterations:  # pad to max_iterations like reference
                pad = _zeros((max_iterations - it,) + s.shape[1:],
                             dtype=s.dtype)
                s = _ops.concat(s, pad, dim=0)
            stacked.append(s)
    else:
        stacked = []
    return (stacked[0] if len(stacked) == 1 else stacked,
            lv[0] if single else lv)


def _while_loop_traced(cond, func, lv, single, max_iterations):
    """Trace-time lowering of while_loop to ``lax.while_loop`` (reference:
    control_flow.cc _while_loop subgraph op inside the graph executor).
    Output buffers are preallocated (max_iterations, *shape) and written
    per iteration, so the stacked-output/zero-pad contract of the eager
    path holds with fully static shapes."""
    import jax
    import jax.numpy as jnp

    lv_data = tuple(v._data for v in lv)

    def fn_body(*jargs):
        outs, new_lv = func(*[NDArray(a) for a in jargs])
        outs = [outs] if isinstance(outs, NDArray) else list(outs)
        new_lv = [new_lv] if isinstance(new_lv, NDArray) else list(new_lv)
        return ([o._data for o in outs], [l._data for l in new_lv])

    out_shapes, lv_shapes = jax.eval_shape(fn_body, *lv_data)
    for s, v in zip(lv_shapes, lv_data):
        if tuple(s.shape) != tuple(v.shape) or s.dtype != v.dtype:
            raise MXNetError(
                "while_loop body must keep loop_vars' shapes/dtypes "
                f"(got {s.shape}/{s.dtype} for {v.shape}/{v.dtype})")
    bufs = tuple(jnp.zeros((max_iterations,) + tuple(s.shape), s.dtype)
                 for s in out_shapes)

    def cond_fn(carry):
        i, lvs, _ = carry
        p = cond(*[NDArray(a) for a in lvs])._data
        return jnp.logical_and(i < max_iterations,
                               p.reshape(()).astype(bool))

    def body_fn(carry):
        i, lvs, bufs = carry
        outs, new_lvs = fn_body(*lvs)
        bufs = tuple(b.at[i].set(o) for b, o in zip(bufs, outs))
        return (i + 1, tuple(new_lvs), bufs)

    _, final_lv, bufs = jax.lax.while_loop(
        cond_fn, body_fn, (jnp.asarray(0, jnp.int32), lv_data, bufs))
    ctx = lv[0].ctx
    stacked = [NDArray(b, ctx=ctx) for b in bufs]
    out_lv = [NDArray(a, ctx=ctx) for a in final_lv]
    return (stacked[0] if len(stacked) == 1 else stacked,
            out_lv[0] if single else out_lv)


def cond(pred, then_func, else_func):
    """Conditional execution (reference: contrib.cond).  With a concrete
    predicate the branch is decided eagerly; a traced predicate
    (hybridize/jit) lowers to ``lax.cond`` — both branches compiled into
    one program, matching the reference's _cond subgraph op.  Under
    lax.cond both branches must produce matching shapes/dtypes (the
    reference's symbolic cond has the same contract)."""
    import jax
    p = pred() if callable(pred) else pred
    if not isinstance(p._data, jax.core.Tracer):
        return then_func() if bool(p.asnumpy()) else else_func()

    def _wrap(branch):
        def fn(_):
            out = branch()
            if isinstance(out, NDArray):
                return out._data
            return tuple(o._data for o in out)
        return fn

    outs = jax.lax.cond(p._data.reshape(()).astype(bool),
                        _wrap(then_func), _wrap(else_func), None)
    ctx = p.ctx
    # single-vs-list structure is preserved by lax.cond's pytree result
    if not isinstance(outs, tuple):
        return NDArray(outs, ctx=ctx)
    return [NDArray(o, ctx=ctx) for o in outs]


# ---------------------------------------------------------------------------
# misc contrib ops
# ---------------------------------------------------------------------------
def isinf(data):
    return _invoke(lambda x: _jnp().isinf(x), [data], name="isinf",
                   differentiable=False)


def isnan(data):
    return _invoke(lambda x: _jnp().isnan(x), [data], name="isnan",
                   differentiable=False)


def isfinite(data):
    return _invoke(lambda x: _jnp().isfinite(x), [data], name="isfinite",
                   differentiable=False)


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """reference: contrib.arange_like — output matches the input's extent
    (full shape, or 1-D of len shape[axis]); each value appears ``repeat``
    times, so the distinct-value count is ceil(n / repeat)."""
    def run(x):
        jnp = _jnp()
        n = x.shape[axis] if axis is not None else x.size
        n_vals = -(-n // repeat)   # ceil
        out = jnp.repeat(start + step * jnp.arange(n_vals, dtype=x.dtype),
                         repeat)[:n]
        if axis is None:
            return out.reshape(x.shape)
        return out
    return _invoke(run, [data], name="arange_like", differentiable=False)


def index_array(data, axes=None):
    """reference: contrib/index_array.cc — coordinates of every element."""
    def run(x):
        jnp = _jnp()
        axes_ = axes if axes is not None else range(x.ndim)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in x.shape],
                             indexing="ij")
        return jnp.stack([grids[a] for a in axes_], -1).astype(jnp.int32)
    return _invoke(run, [data], name="index_array", differentiable=False)


def index_copy(old_tensor, index_vector, new_tensor):
    """reference: contrib/index_copy.cc — rows of new copied into old."""
    def run(old, idx, new):
        return old.at[idx].set(new)
    return _invoke(run, [old_tensor, index_vector, new_tensor],
                   name="index_copy")


def boolean_mask(data, index, axis=0):
    """Select rows where index != 0 (reference: contrib/boolean_mask.cc).
    Data-dependent output shape: eager-only; under jit use where/topk
    patterns instead.  Delegates to the nd-level op."""
    from .ops import boolean_mask as _bm
    return _bm(data, index, axis=axis)


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """a*x^2 + b*x + c (reference: the contrib tutorial op
    quadratic_op-inl.h)."""
    def run(x):
        return a * x * x + b * x + c
    return _invoke(run, [data], name="quadratic")


def getnnz(data, axis=None):
    """Number of stored values of a CSR (reference: contrib getnnz /
    nnz of sparse storage)."""
    from . import sparse as _sp
    from .ndarray import array as _array
    import numpy as _onp
    if isinstance(data, _sp.CSRNDArray):
        if axis is None:
            return _array(_onp.asarray([data._cs_indices.shape[0]],
                                       _onp.int64))
        if axis == 1:
            ptr = _onp.asarray(data._cs_indptr)
            return _array((ptr[1:] - ptr[:-1]).astype(_onp.int64))
        raise MXNetError("getnnz: axis must be None or 1 for CSR")
    d = data.asnumpy()
    return _array(_onp.asarray([(d != 0).sum()], _onp.int64))


def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=True):
    """1.0 if all elements are close (reference: contrib/allclose_op.cc)."""
    def run(x, y):
        jnp = _jnp()
        return jnp.allclose(x, y, rtol=rtol, atol=atol,
                            equal_nan=equal_nan).astype(jnp.float32)
    return _invoke(run, [a, b], name="allclose", differentiable=False)


def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             blank_label="first", **kw):
    """Connectionist temporal classification loss (reference:
    contrib.ctc_loss; the gluon CTCLoss is the same kernel).  data:
    (T, B, C) activations; label: (B, L) padded with -1."""
    if kw:
        raise MXNetError(f"ctc_loss: unsupported arguments {sorted(kw)}")
    if blank_label != "first":
        raise MXNetError(
            "ctc_loss: only blank_label='first' is implemented in this "
            "build")
    from ..gluon.loss import CTCLoss as _G
    loss = _G(layout="TNC", label_layout="NT")
    return loss(data, label, data_lengths, label_lengths)


CTCLoss = ctc_loss


def count_sketch(data, h, s, out_dim, processing_batch_size=32,
                 **_ignored):
    """Count-sketch projection (reference: src/operator/contrib/
    count_sketch.cc — the compact-bilinear-pooling primitive):
    ``out[n, h[i]] += s[i] * data[n, i]``.  ``h`` holds hash buckets in
    [0, out_dim), ``s`` signs of +-1; both may carry the reference's
    leading singleton axis.  One scatter-add on TPU — XLA lowers the
    duplicate-index .at[].add to a sorted segment reduction, and its
    VJP (dx = s * dout[:, h]) is a plain gather, so no custom gradient
    is needed.  processing_batch_size is the reference's GPU chunking
    knob — meaningless here, accepted for parity."""
    def run(x, hh, ss):
        jnp = _jnp()
        hh = hh.reshape(-1).astype(jnp.int32)
        ss = ss.reshape(-1).astype(x.dtype)
        out = jnp.zeros(x.shape[:-1] + (int(out_dim),), x.dtype)
        return out.at[..., hh].add(x * ss)
    return _invoke(run, [data, h, s], name="count_sketch")


def PSROIPooling(data, rois, spatial_scale, output_dim, pooled_size,
                 group_size=0, **_ignored):
    """Position-sensitive ROI pooling (reference: src/operator/contrib/
    psroi_pooling.cc — the R-FCN head).  data (B, output_dim*group^2,
    H, W); rois (R, 5) [batch_idx, x0, y0, x1, y1] in image coords.
    Output bin (i, j) of channel d AVERAGES input channel
    (d*group + gi)*group + gj over the bin's pixels, where (gi, gj) is
    the bin's position group.  Empty bins give 0, matching the
    reference."""
    g = int(group_size) or int(pooled_size)
    p = int(pooled_size)

    def run(x, r):
        import jax
        jnp = _jnp()
        B, C, H, W = x.shape
        if C != output_dim * g * g:
            raise MXNetError(
                f"PSROIPooling: data has {C} channels, needs "
                f"output_dim*group_size^2 = {output_dim * g * g}")

        def cround(v):
            # C round(): half away from zero — jnp.round is half-to-even,
            # which would shift bins for *.5 proposal coords
            return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

        def one_roi(roi):
            bidx = roi[0].astype(jnp.int32)
            x0 = cround(roi[1]) * spatial_scale
            y0 = cround(roi[2]) * spatial_scale
            x1 = cround(roi[3] + 1.0) * spatial_scale
            y1 = cround(roi[4] + 1.0) * spatial_scale
            rw = jnp.maximum(x1 - x0, 0.1)   # reference's min extent
            rh = jnp.maximum(y1 - y0, 0.1)
            img = x[bidx].reshape(output_dim, g * g, H, W)
            iy = jnp.arange(H, dtype=x.dtype)
            ix = jnp.arange(W, dtype=x.dtype)
            bins = []
            for i in range(p):
                ys = jnp.floor(y0 + i * rh / p)
                ye = jnp.ceil(y0 + (i + 1) * rh / p)
                my = (iy >= ys) & (iy < ye)
                gi = min(i * g // p, g - 1)
                for j in range(p):
                    xs = jnp.floor(x0 + j * rw / p)
                    xe = jnp.ceil(x0 + (j + 1) * rw / p)
                    mxv = (ix >= xs) & (ix < xe)
                    m = (my[:, None] & mxv[None, :]).astype(x.dtype)
                    gj = min(j * g // p, g - 1)
                    cnt = jnp.maximum(jnp.sum(m), 1.0)
                    # slice the bin's position-group channel plane
                    plane = img[:, gi * g + gj]          # (D, H, W)
                    bins.append(jnp.sum(plane * m[None], (-1, -2)) / cnt)
            return jnp.stack(bins, -1).reshape(output_dim, p, p)
        return jax.vmap(one_roi)(r)          # (R, D, p, p)
    return _invoke(run, [data, rois], name="PSROIPooling")


psroipooling = PSROIPooling


def fft(data, compute_size=128):
    """Alias of the packed-layout FFT (reference: contrib fft.cc)."""
    from .ops_ext import fft as _fft
    return _fft(data, compute_size)


def ifft(data, compute_size=128):
    from .ops_ext import ifft as _ifft
    return _ifft(data, compute_size)


def interleaved_matmul_selfatt_qk(queries_keys_values, heads):
    """Attention scores from interleaved QKV projections (reference:
    contrib/transformer.cc interleaved_matmul_selfatt_qk, the 1.6 fused
    MHA ops).  Input (T, B, 3*H*D) with per-head interleaved [q, k, v];
    output (B*H, T, T) scaled scores."""
    def run(qkv):
        jnp = _jnp()
        T, B, P = qkv.shape
        hd = P // (3 * heads)
        x = qkv.reshape(T, B, heads, 3, hd)
        q = x[:, :, :, 0]                   # (T, B, H, D)
        k = x[:, :, :, 1]
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, qkv.dtype))
        s = jnp.einsum("qbhd,kbhd->bhqk", q * scale, k)
        return s.reshape(B * heads, T, T)
    return _invoke(run, [queries_keys_values],
                   name="interleaved_matmul_selfatt_qk")


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads):
    """Apply attention weights to interleaved values (reference:
    contrib/transformer.cc interleaved_matmul_selfatt_valatt).
    qkv (T, B, 3*H*D) + att (B*H, T, T) -> (T, B, H*D)."""
    def run(qkv, att):
        jnp = _jnp()
        T, B, P = qkv.shape
        hd = P // (3 * heads)
        v = qkv.reshape(T, B, heads, 3, hd)[:, :, :, 2]  # (T, B, H, D)
        a = att.reshape(B, heads, T, T)
        out = jnp.einsum("bhqk,kbhd->qbhd", a, v)
        return out.reshape(T, B, heads * hd)
    return _invoke(run, [queries_keys_values, attention],
                   name="interleaved_matmul_selfatt_valatt")


def Proposal(cls_prob, bbox_pred, im_info, feature_stride=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
             threshold=0.7, rpn_min_size=16):
    """RPN proposal op (reference: contrib/proposal.cc): decode anchor
    deltas, clip to the image, filter small boxes, NMS, keep top-N.
    Fixed-size output (B, rpn_post_nms_top_n, 5) [batch_idx, x0,y0,x1,y1]
    with -1 rows invalid — the XLA-friendly re-derivation of the CUDA
    kernel's dynamic shapes."""
    def run(prob, pred, info):
        import jax
        jnp = _jnp()
        B, A2, H, W = prob.shape
        A = A2 // 2
        # base anchors at stride cells (corner format, centered)
        base = []
        for sc in scales:
            for r in ratios:
                ws = feature_stride * sc * (r ** 0.5)
                hs = feature_stride * sc / (r ** 0.5)
                base.append((-ws / 2, -hs / 2, ws / 2, hs / 2))
        base = jnp.asarray(base, prob.dtype)          # (A, 4)
        gy, gx = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing="ij")
        ctr = jnp.stack([gx, gy, gx, gy], -1) * feature_stride \
            + feature_stride / 2.0                     # (H, W, 4)
        anchors = (ctr[:, :, None, :] + base[None, None]).reshape(-1, 4)
        N = anchors.shape[0]

        fg = prob[:, A:].transpose(0, 2, 3, 1).reshape(B, N)
        deltas = pred.transpose(0, 2, 3, 1).reshape(B, N, 4)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        cx = deltas[..., 0] * aw + acx
        cy = deltas[..., 1] * ah + acy
        w = jnp.exp(jnp.clip(deltas[..., 2], -8, 8)) * aw
        h = jnp.exp(jnp.clip(deltas[..., 3], -8, 8)) * ah
        x0 = jnp.clip(cx - w / 2, 0, info[:, 1:2] - 1)
        y0 = jnp.clip(cy - h / 2, 0, info[:, 0:1] - 1)
        x1 = jnp.clip(cx + w / 2, 0, info[:, 1:2] - 1)
        y1 = jnp.clip(cy + h / 2, 0, info[:, 0:1] - 1)
        # reference filters at rpn_min_size * image scale (im_info[2])
        min_sz = rpn_min_size * info[:, 2:3]
        keep = ((x1 - x0 + 1 >= min_sz) & (y1 - y0 + 1 >= min_sz))
        score = jnp.where(keep, fg, -1.0)
        k = min(rpn_pre_nms_top_n, N)
        top_s, top_i = jax.lax.top_k(score, k)
        bsel = jnp.arange(B)[:, None]
        boxes = jnp.stack([x0[bsel, top_i], y0[bsel, top_i],
                           x1[bsel, top_i], y1[bsel, top_i]], -1)
        # per-batch greedy NMS over the top-k, fixed output size
        rows = jnp.concatenate(
            [jnp.zeros((B, k, 1), prob.dtype),    # single fg class id 0
             top_s[..., None], boxes], -1)
        return rows
    raw = _invoke(run, [cls_prob, bbox_pred, im_info], name="Proposal",
                  differentiable=False)
    # NMS over ALL pre-NMS candidates (reference order: suppress first,
    # THEN keep the top rpn_post_nms_top_n survivors)
    kept = box_nms(raw, overlap_thresh=threshold, valid_thresh=0.0,
                   topk=-1, coord_start=2, score_index=1, id_index=0)

    def pack(r):
        jnp = _jnp()
        B, N = r.shape[0], r.shape[1]
        n = rpn_post_nms_top_n
        # box_nms output is score-sorted with -1 gaps; compact survivors
        # to the front, then truncate to the fixed post-NMS count
        valid = r[..., 0] >= 0
        order = jnp.argsort(~valid, axis=1, stable=True)
        bsel = jnp.arange(B)[:, None]
        rows = r[bsel, order][:, :n]
        valid_n = rows[..., 0] >= 0
        bidx = jnp.broadcast_to(
            jnp.arange(B, dtype=r.dtype)[:, None], (B, n))
        out = jnp.concatenate(
            [jnp.where(valid_n, bidx, -1.0)[..., None], rows[..., 2:6]],
            -1)
        return out
    return _invoke(pack, [kept], name="Proposal_pack",
                   differentiable=False)
