"""``mx.nd.random`` namespace (reference: src/operator/random/sample_op.cc;
python/mxnet/ndarray/random.py).  Samplers draw keys from the per-context
stream in ``incubator_mxnet_tpu.random``."""
from __future__ import annotations

import numpy as _np

from .. import random as _random
from ..context import current_context
from .ndarray import NDArray, _invoke, _place

__all__ = ["uniform", "normal", "randn", "randint", "poisson", "exponential",
           "gamma", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle", "bernoulli"]


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = jax.random.uniform(key, _shape(shape), dtype=_np.dtype(dtype),
                             minval=low, maxval=high)
    return _place(out, ctx)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = loc + scale * jax.random.normal(key, _shape(shape),
                                          dtype=_np.dtype(dtype))
    return _place(out, ctx)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype=dtype, ctx=ctx)


def randint(low, high, shape=None, dtype="int32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = jax.random.randint(key, _shape(shape), low, high,
                             dtype=_np.dtype(dtype))
    return _place(out, ctx)


def poisson(lam=1.0, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = jax.random.poisson(key, lam, _shape(shape)).astype(_np.dtype(dtype))
    return _place(out, ctx)


def exponential(scale=1.0, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = scale * jax.random.exponential(key, _shape(shape),
                                         dtype=_np.dtype(dtype))
    return _place(out, ctx)


def gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = beta * jax.random.gamma(key, alpha, _shape(shape),
                                  dtype=_np.dtype(dtype))
    return _place(out, ctx)


def negative_binomial(k=1, p=1.0, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key1 = _random.new_key(ctx)
    key2 = _random.new_key(ctx)
    lam = jax.random.gamma(key1, k, _shape(shape)) * (1 - p) / p
    out = jax.random.poisson(key2, lam).astype(_np.dtype(dtype))
    return _place(out, ctx)


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=None,
                                  dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key1 = _random.new_key(ctx)
    key2 = _random.new_key(ctx)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(key1, r, _shape(shape)) * (1 - p) / p
    out = jax.random.poisson(key2, lam).astype(_np.dtype(dtype))
    return _place(out, ctx)


def bernoulli(prob=0.5, shape=None, dtype="float32", ctx=None, **kw):
    import jax
    ctx = ctx or current_context()
    key = _random.new_key(ctx)
    out = jax.random.bernoulli(key, prob, _shape(shape)).astype(
        _np.dtype(dtype))
    return _place(out, ctx)


def multinomial(data, shape=None, get_prob=False, dtype="int32", **kw):
    """Sample category indices from probability rows (reference:
    sample_multinomial)."""
    import jax
    from .ndarray import array as _array
    d = data if isinstance(data, NDArray) else _array(_np.asarray(data))
    ctx = d.ctx
    key = _random.new_key(ctx)
    n = 1 if shape is None else int(_np.prod(_shape(shape)))

    def fn(p):
        import jax.numpy as jnp
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if p.ndim == 1:
            out = jax.random.categorical(key, logits, shape=(n,))
            return (out[0] if shape is None else
                    out.reshape(_shape(shape))).astype(dtype)
        out = jax.random.categorical(key, logits[:, None, :], axis=-1,
                                     shape=(p.shape[0], n))
        if shape is None:
            out = out[:, 0]
        else:
            out = out.reshape((p.shape[0],) + _shape(shape))
        return out.astype(dtype)
    return _invoke(fn, [d], name="multinomial", differentiable=False)


def shuffle(data, **kw):
    import jax
    d = data
    key = _random.new_key(d.ctx)
    return _invoke(lambda x: jax.random.permutation(key, x, axis=0), [d],
                   name="shuffle", differentiable=False)
