"""``mx.recordio`` — alias of :mod:`incubator_mxnet_tpu.io.recordio`
(the reference exposes the same module at both ``mx.recordio`` and via
``mx.io``; reference: python/mxnet/recordio.py)."""
from .io.recordio import (MXRecordIO, MXIndexedRecordIO, IndexedRecordIO,
                          IRHeader, pack, unpack, pack_img, unpack_img)

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IndexedRecordIO",
           "IRHeader", "pack", "unpack", "pack_img", "unpack_img"]
