"""``mx.engine`` — execution-engine control shims (reference:
python/mxnet/engine.py; src/engine/*).

The reference's dependency engine does not exist here: jax's async dispatch
plus XLA scheduling subsume it (SURVEY.md §7.0).  What remains meaningful:

* ``MXNET_ENGINE_TYPE=NaiveEngine`` — the reference's synchronous debugging
  oracle (reference: src/engine/naive_engine.cc).  Here it forces every
  eager op to block until computed, which serializes execution exactly the
  same way; async-vs-sync bug bisection works identically.
* ``bulk`` — the reference batches engine pushes
  (MXNET_EXEC_BULK_EXEC_*); XLA fuses compiled programs already, so the
  scope is kept for API compatibility and tracks its size setting only.
"""
from __future__ import annotations

from .base import getenv

__all__ = ["bulk", "set_bulk_size", "get_bulk_size", "set_engine_type",
           "get_engine_type"]

_bulk_size = 15
_engine_type = "ThreadedEnginePerDevice"


def _nd_mod():
    from .ndarray import ndarray as nd_mod
    return nd_mod


def set_engine_type(name: str) -> str:
    """'NaiveEngine' → synchronous dispatch; anything else → async
    (the default).  Returns the previous engine name."""
    global _engine_type
    prev = _engine_type
    _engine_type = name
    _nd_mod()._sync_dispatch = (name == "NaiveEngine")
    return prev


def get_engine_type() -> str:
    return _engine_type


def set_bulk_size(size: int) -> int:
    """reference: mx.engine.set_bulk_size — returns previous value."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


def get_bulk_size() -> int:
    return _bulk_size


class bulk:
    """Scope marking a bulked segment (reference: mx.engine.bulk).  XLA
    fuses compiled regions regardless; the scope only tracks the size."""

    def __init__(self, size: int):
        self._size = size
        self._prev = None

    def __enter__(self):
        self._prev = set_bulk_size(self._size)
        return self

    def __exit__(self, *exc):
        set_bulk_size(self._prev)


_env_engine = getenv("MXNET_ENGINE_TYPE")
if _env_engine:
    set_engine_type(_env_engine)
