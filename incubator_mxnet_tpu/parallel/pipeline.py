"""Pipeline parallelism as ONE SPMD program — GPipe and 1F1B schedules,
composing with tensor parallelism into 3D (data x pipe x model)
(reference analog: the reference had no pipeline engine — its
distributed story was data parallelism over kvstore; this is the
beyond-parity axis completing dp/tp/sp/ep/pp/fsdp.  Pattern: the
pipelined-scan recipe of the TPU scaling playbook — stack homogeneous
stage parameters, shard the stack over a mesh axis, stream microbatches
around the ring with ppermute inside lax.scan; 1F1B writes the backward
out explicitly for O(S) activation memory; tensor axes ride GSPMD auto
mode inside the pipe-explicit schedule).

Design:
  * stage parameters are STACKED pytrees — every leaf (S, ...) — and
    sharded over the ``pipe`` mesh axis, so placement is a
    PartitionSpec, exactly like tensor/expert parallelism here;
  * the schedule runs M + S - 1 ticks; every device runs the SAME
    program each tick (SPMD — idle bubble ticks compute on garbage and
    are masked), activations hop stage->stage+1 via ppermute over ICI;
  * differentiable end to end: lax.scan + ppermute transpose cleanly,
    so jax.grad/SPMDTrainer-style training through the pipeline needs
    nothing special;
  * microbatches enter replicated; outputs are collected on the last
    stage and replicated back with a psum — callers see a plain
    (M, ...) array.
"""
from __future__ import annotations

from typing import Any, Callable

from ..base import MXNetError


def _require_single_output(outs):
    """The stage protocol carries ONE activation tensor between pipe
    ranks; anything else (e.g. MoE's (y, aux)) would be silently
    truncated at outs[0]."""
    if len(outs) != 1:
        raise MXNetError(
            "pipeline stages must return exactly one activation "
            f"tensor, got {len(outs)} outputs — multi-output cells "
            "(e.g. MoE's (y, aux)) cannot ride the stage protocol; "
            "use expert parallelism (moe.ep_rules) instead")
    return outs[0]

__all__ = ["gpipe", "stack_stage_params", "pipe_specs",
           "stack_block_stages", "PipelineTrainer"]


def stack_block_stages(blocks, training=False, rng_key=None):
    """Turn a list of same-architecture (initialized, shape-settled)
    Blocks into pipeline stages: returns ``(stage_fn, stacked_params)``
    for :func:`gpipe`.  The first block is the template whose forward
    runs functionally with each stage's parameter values substituted —
    the ONE place the cell-as-stage recipe lives (used by the driver
    dryrun and the tests alike).

    ``training`` selects the train-mode forward.  Stage calls are pure
    fn(params, x): STOCHASTIC layers would get the one ``rng_key`` on
    every call and AUXILIARY state (BatchNorm running stats) has no way
    out of the schedule — so training=True REFUSES blocks with active
    Dropout or aux state rather than silently mis-sampling/stale-ing
    them.  Build pipelined stages from deterministic, stateless layers
    (LayerNorm etc.), the standard pipeline practice."""
    import jax
    from ..gluon.block import functional_call
    from ..ndarray.ndarray import NDArray
    if not blocks:
        raise MXNetError("stack_block_stages needs >= 1 block")
    template = blocks[0]
    if training:
        _refuse_impure(template, "stack_block_stages(training=True)")
    trainable = list(template.collect_params().values())
    if any(p.grad_req == "null" for p in trainable) and training:
        raise MXNetError(
            "stack_block_stages(training=True) with auxiliary state "
            "(BatchNorm running stats): the pure stage contract cannot "
            "carry aux updates out of the schedule — use stateless "
            "normalization (LayerNorm/GroupNorm) in pipelined stages")
    # readable keys: strip the template's own prefix; stages align by
    # POSITION (collect_params order is construction order, identical
    # for same-architecture blocks), so a key collision — possible with
    # prefix='' where child names carry no shared block prefix — falls
    # back to enumerated keys rather than silently merging params
    pfx = getattr(template, "prefix", "") or ""
    names = [p.name[len(pfx):] if pfx and p.name.startswith(pfx)
             else p.name for p in trainable]
    if len(set(names)) != len(names):
        names = [f"p{i}_{n}" for i, n in enumerate(names)]
    trees = []
    for b in blocks:
        ps = list(b.collect_params().values())
        if len(ps) != len(names):
            raise MXNetError("stage blocks differ in parameter count")
        trees.append({n: p.data()._data for n, p in zip(names, ps)})
    stacked = stack_stage_params(trees)
    key = rng_key if rng_key is not None else jax.random.PRNGKey(0)

    def stage_fn(p, x):
        outs, _ = functional_call(template, trainable,
                                  [p[n] for n in names], [], [],
                                  [NDArray(x)], training, key)
        return _require_single_output(outs)

    return stage_fn, stacked


def stack_stage_params(param_trees):
    """Stack per-stage parameter pytrees (a list of S same-structure
    trees) into one tree whose leaves carry a leading stage axis."""
    import jax
    import jax.numpy as jnp
    if not param_trees:
        raise MXNetError("stack_stage_params needs >= 1 stage tree")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def pipe_specs(stacked_params, axis="pipe"):
    """PartitionSpecs sharding every leaf's leading (stage) axis."""
    import jax
    from jax.sharding import PartitionSpec as P

    def leaf(v):
        return P(axis, *([None] * (v.ndim - 1)))
    return jax.tree.map(leaf, stacked_params)


def gpipe(stage_fn: Callable[[Any, Any], Any], stacked_params, xs,
          mesh, axis: str = "pipe"):
    """Apply S pipeline stages to M microbatches.

    stage_fn(params, x) -> y : one stage's computation (same shape in
    and out — the transformer-layer contract); ``stacked_params``:
    pytree with leading stage dim S == mesh.shape[axis];
    ``xs``: (M, ...) microbatched activations.  Returns (M, ...) — the
    composition stage_{S-1}(...stage_0(x)) per microbatch, replicated.

    Wall-clock is (M + S - 1)/M of the ideal — the GPipe bubble; raise
    M to amortize.  Gradients flow through (scan + ppermute transpose).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ._shmap import shard_map

    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis!r}")
    S = mesh.shape[axis]
    M = xs.shape[0]
    leading = {v.shape[0] for v in jax.tree.leaves(stacked_params)}
    if leading != {S}:
        raise MXNetError(
            f"stacked_params leading dims {sorted(leading)} != pipe "
            f"axis size {S}")

    def body(params_local, xs_rep):
        stage = jax.lax.axis_index(axis)
        p = jax.tree.map(lambda a: a[0], params_local)  # this stage's
        buf = jnp.zeros_like(xs_rep[0])
        ys0 = jnp.zeros_like(xs_rep)

        def tick(carry, t):
            buf, ys = carry
            # stage 0 ingests microbatch t (clipped reads during the
            # drain phase are masked out downstream)
            inp = jnp.where(stage == 0,
                            xs_rep[jnp.clip(t, 0, M - 1)], buf)
            out = stage_fn(p, inp)
            # the last stage owns microbatch t - stage at this tick
            idx = jnp.clip(t - stage, 0, M - 1)
            valid = (stage == S - 1) & (t >= stage) & (t < stage + M)
            ys = ys.at[idx].set(jnp.where(valid, out, ys[idx]))
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, ys), None

        (_, ys), _ = jax.lax.scan(tick, (buf, ys0),
                                  jnp.arange(M + S - 1))
        # only the last stage holds real outputs; psum replicates them
        ys = jnp.where(stage == S - 1, ys, jnp.zeros_like(ys))
        return jax.lax.psum(ys, axis)

    in_specs = (pipe_specs(stacked_params, axis), P())
    return shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=P(),
                     check_vma=False)(stacked_params, xs)


def _refuse_impure(net, what):
    """The pure-stage contract shared with stack_block_stages: stochastic
    layers would reuse one RNG key across stages/microbatches and aux
    state (BatchNorm stats) has no way out of the schedule."""
    from ..gluon import nn as _nn
    drops = []
    net.apply(lambda b: drops.append(b) if isinstance(b, _nn.Dropout)
              and getattr(b, "_rate", 0) else None)
    if drops:
        raise MXNetError(
            f"{what} with active Dropout: build the net with dropout=0 "
            "(the pure stage contract cannot thread per-stage RNG)")


from .spmd import SPMDTrainer as _SPMDTrainer  # noqa: E402


class PipelineTrainer(_SPMDTrainer):
    """GPipe pipeline-parallel TRAINING as one compiled SPMD program over
    a ``data`` x ``pipe`` mesh (typically reached via
    ``SPMDTrainer(..., pipeline_axis="pipe")``).

    Stage assignment is Megatron's: every stage runs an equal contiguous
    slice of the model's transformer cells; stage 0 additionally runs
    the embedding ("first") work and the LAST stage the final-norm +
    head ("last") work plus the loss, so activations crossing stages are
    uniformly (b, T, C) and the collected per-microbatch output is a
    scalar loss.  The model describes that split via
    ``pipeline_split() -> (first_params, first_fn, cells, last_params,
    last_fn)`` where ``first_fn(first_vals, ids) -> x`` embeds a
    microbatch and ``last_fn(last_vals, first_vals, x) -> outputs``
    produces what the loss block consumes (``first_vals`` is passed back
    so tied heads — GPT's logits through the embedding matrix — stay
    tied; both gradient contributions sum via the pipe-axis psum the
    shard_map transpose inserts).

    Parameter placement is pure sharding, like every other axis here:
    cell parameters are STACKED (S, ...) pytrees sharded over ``pipe``
    (each device holds only its stages' weights — the memory win
    pipeline parallelism exists for); first/last parameters ride
    replicated.  The optimizer state inherits each leaf's sharding, so
    cell-state memory also scales 1/S.  The batch axis shards over
    ``data`` exactly as in SPMDTrainer; grad all-reduce is the compiled
    psum.

    Schedules (``pipeline_schedule=``):
      * ``"gpipe"`` (default) — M microbatches forward over M + S - 1
        ticks, backward via AD's scan transpose; peak activation memory
        grows with M (every tick's residuals are saved).
      * ``"1f1b"`` — one forward AND one backward microbatch per tick,
        backward hand-written (per-stage vjp, explicit cotangent hops,
        remat of the stage forward from a 2S-deep input stash); peak
        activation memory is O(S), INDEPENDENT of M — raise
        ``pipeline_microbatches`` to shrink the bubble for free.
    Both schedules compute identical math (the trainer tests prove
    loss- and trained-parameter-parity against the 1-device oracle).
    Every tick every device runs the same program (SPMD): non-owning
    stages compute first/last work into a discarded ``where`` branch —
    wasted FLOPs linear in (first+last)/stage cost, the price of
    single-program form.

    Restrictions (all raise): dropout > 0 anywhere in the net, aux state
    (BatchNorm) in cells, ``lamb`` (its per-TENSOR trust ratio sees the
    stacked (S, ...) tensor, changing the math vs the unstacked oracle),
    len(cells) % S != 0, and local batch % microbatches != 0.

    Reference analog: none — the reference's distributed story stops at
    data parallelism over kvstore (SURVEY §2.4); this is the pp axis of
    the beyond-parity dp/tp/sp/ep/pp set, trained end to end.
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_axis="data", sharding_rules=None,
                 extra_input_shardings=None, donate=True,
                 shard_optimizer_state=False, zero1=None,
                 pipeline_axis="pipe",
                 pipeline_microbatches=None, pipeline_schedule=None,
                 accum_steps=None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from . import mesh as mesh_mod
        from . import optim as fopt

        if accum_steps not in (None, 1):
            raise MXNetError(
                "accum_steps does not apply to the pipeline trainer — "
                "pipeline_microbatches already streams the batch in "
                "microbatches (raise it for the same memory effect)")
        if extra_input_shardings or shard_optimizer_state or zero1:
            raise MXNetError(
                "pipeline_axis does not compose with "
                "extra_input_shardings / shard_optimizer_state / zero1 "
                "yet — cell params are already sharded over the pipe "
                "axis (their optimizer state with them).  sharding_rules "
                "DO compose: tensor-parallel specs apply on top of the "
                "stage stacking (3D dp x pipe x model parallelism)")
        self._rules = list(sharding_rules or [])
        self._net = net
        self._loss = loss_fn
        self._mesh = mesh or mesh_mod.current_mesh()
        if self._mesh is None:
            raise MXNetError("PipelineTrainer needs a mesh")
        for ax in (data_axis, pipeline_axis):
            if ax not in self._mesh.shape:
                raise MXNetError(f"mesh has no axis {ax!r}")
        from jax.sharding import PartitionSpec as _P
        for _pat, _sp in self._rules:
            entries = tuple(_sp) if isinstance(_sp, (list, tuple, _P)) \
                else (_sp,)
            for entry in entries:
                for ax in (entry if isinstance(entry, tuple)
                           else (entry,)):
                    if ax is None:
                        continue
                    if ax not in self._mesh.shape:
                        raise MXNetError(
                            f"sharding_rules: axis {ax!r} (rule {_pat!r})"
                            f" not in the mesh {tuple(self._mesh.shape)} "
                            "— a 3D pipeline needs the tensor axis in "
                            "the mesh, e.g. make_mesh({'data': d, "
                            "'pipe': s, 'model': t})")
                    if ax in (data_axis, pipeline_axis):
                        raise MXNetError(
                            f"sharding_rules: axis {ax!r} (rule {_pat!r})"
                            " is a schedule-owned (manual) axis — the "
                            "pipeline already shards stages over "
                            f"{pipeline_axis!r} and the batch over "
                            f"{data_axis!r}; tensor rules may only use "
                            "other mesh axes (e.g. 'model')")
        self._data_axis = data_axis
        self._pipe_axis = pipeline_axis
        self._S = S = self._mesh.shape[pipeline_axis]
        self._donate = donate
        if optimizer == "lamb":
            raise MXNetError(
                "lamb is not stage-stacking-safe (per-tensor trust "
                "ratio over the stacked (S, ...) tensor differs from "
                "per-stage); use sgd/adam")
        self._opt = fopt.create(optimizer, **(optimizer_params or {}))

        if not hasattr(net, "pipeline_split"):
            raise MXNetError(
                f"{type(net).__name__} does not implement "
                "pipeline_split(); see models/gpt.py for the protocol")
        (self._first_params, self._first_fn, cells,
         self._last_params, self._last_fn) = net.pipeline_split()
        _refuse_impure(net, "PipelineTrainer")
        sp_axes = set()
        net.apply(lambda b: sp_axes.add(getattr(b, "_seq_axis", None)))
        if sp_axes - {None}:
            raise MXNetError(
                "pipeline does not compose with sequence parallelism "
                f"(net carries seq_axis={sorted(sp_axes - {None})}): "
                "ring/ulysses build their own shard_map inside the "
                "stage body — nested manual collectives; build the net "
                "without seq_axis and use tensor parallelism "
                "(sharding_rules=tp_rules(block=net)) for the "
                "attention instead")
        if len(cells) % S:
            raise MXNetError(
                f"{len(cells)} cells do not split over pipe axis {S}")
        self._L = L = len(cells) // S
        self._cells = cells
        self._cell_trainables = []
        n_per_cell = None
        for c in cells:
            ps = list(c.collect_params().values())
            if any(p.grad_req == "null" for p in ps):
                raise MXNetError(
                    "pipelined cells with auxiliary state (BatchNorm "
                    "running stats) are unsupported — use stateless "
                    "normalization (LayerNorm)")
            if n_per_cell is None:
                n_per_cell = len(ps)
            elif len(ps) != n_per_cell:
                raise MXNetError("cells differ in parameter count")
            self._cell_trainables.append(ps)
        for p in (list(self._first_params) + list(self._last_params)
                  + [q for ps in self._cell_trainables for q in ps]):
            if p._data is None:
                raise MXNetError(
                    "initialize the net and run one forward before "
                    "building a PipelineTrainer")

        # one matcher for the whole trainer: shard_params gives
        # first-match resolution AND the dead-rule warning the tp_rules
        # docstrings promise (a rule matching nothing silently
        # replicates the weights it meant to shard).  EVERY trainable
        # name participates so per-stage exact-name rules count as live.
        from .spmd import shard_params as _shard_params
        all_named = {p.name: p.data()._data
                     for p in (list(self._first_params)
                               + list(self._last_params)
                               + [q for ps in self._cell_trainables
                                  for q in ps])}
        rule_sh = _shard_params(all_named, self._mesh, self._rules)

        def _tp_spec(name, ndim):
            """The matched rule's spec, None-padded to ndim (all-None =
            replicated on the tensor axes)."""
            entries = list(rule_sh[name].spec)
            entries += [None] * (ndim - len(entries))
            return tuple(entries)

        def pipe_sh(tp_spec):
            # stage axis first, then the cell param's own TP spec —
            # 3D parallelism is just this composition of PartitionSpecs
            return NamedSharding(self._mesh, P(pipeline_axis, *tp_spec))

        # placed COPIES (same donation-safety reasoning as SPMDTrainer)
        self._first_vals = tuple(
            jnp.copy(jax.device_put(p.data()._data, rule_sh[p.name]))
            for p in self._first_params)
        self._last_vals = tuple(
            jnp.copy(jax.device_put(p.data()._data, rule_sh[p.name]))
            for p in self._last_params)
        stacked = {}
        for j in range(L):
            for i in range(n_per_cell):
                vals = [self._cell_trainables[s * L + j][i].data()._data
                        for s in range(S)]
                v = jnp.stack(vals)
                # the TP spec comes from the TEMPLATE cell's param name;
                # same-architecture stages shard identically (rules from
                # tp_rules(block=net) carry exact per-cell names — the
                # template's is the canonical one for its position)
                tp = _tp_spec(self._cell_trainables[j][i].name,
                              v.ndim - 1)
                stacked[f"c{j}_p{i}"] = jnp.copy(
                    jax.device_put(v, pipe_sh(tp)))
        self._stacked = stacked
        self._opt_state = self._opt.init(
            (self._first_vals, self._stacked, self._last_vals))
        self._M = S if pipeline_microbatches is None \
            else int(pipeline_microbatches)
        if self._M < 1:
            raise MXNetError("pipeline_microbatches must be >= 1")
        self._schedule = pipeline_schedule or "gpipe"
        if self._schedule not in ("gpipe", "1f1b"):
            raise MXNetError(
                f"unknown pipeline_schedule {self._schedule!r} "
                "(gpipe | 1f1b)")
        self._step_count = 0
        self._jit_cache = {}

    # _shard_batch / mesh come from SPMDTrainer (whose __init__ this
    # class REPLACES rather than extends — the parameter storage is
    # stacked-by-stage, not per-Parameter)

    @property
    def params(self):
        out = {p.name: v for p, v in
               zip(self._first_params, self._first_vals)}
        out.update({p.name: v for p, v in
                    zip(self._last_params, self._last_vals)})
        from .spmd import _fetch_full
        L, S = self._L, self._S
        for j in range(L):
            for i in range(len(self._cell_trainables[0])):
                # allgather first: pipe-sharded stacked leaves are not
                # fully addressable on a multi-host mesh (same routing
                # sync_to_block uses)
                leaf = _fetch_full(self._stacked[f"c{j}_p{i}"])
                for s in range(S):
                    out[self._cell_trainables[s * L + j][i].name] = \
                        leaf[s]
        return out

    def _build_step(self):
        if self._schedule == "1f1b":
            return self._build_step_1f1b()
        return self._build_step_gpipe()

    def _stage_closures(self):
        """The per-stage forward + loss-head closures shared by both
        schedules (templates captured once; pure fn(params, x))."""
        import jax
        import jax.numpy as jnp
        from ..gluon.block import functional_call
        from ..ndarray.ndarray import NDArray
        from .. import autograd as _ag

        L = self._L
        templates = self._cells[:L]
        tmpl_params = self._cell_trainables[:L]
        n_per_cell = len(tmpl_params[0])
        last_fn, loss_blk = self._last_fn, self._loss
        key = jax.random.PRNGKey(0)   # dropout refused: never consumed

        def stage_fn(tree, x):
            for j in range(L):
                vals = [tree[f"c{j}_p{i}"] for i in range(n_per_cell)]
                outs, _ = functional_call(
                    templates[j], tmpl_params[j], vals, [], [],
                    [NDArray(x)], True, key)
                x = _require_single_output(outs)
            return x

        def mb_loss(lv, fv, out, labels):
            outs = last_fn(lv, fv, out)
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            with _ag.pause(train_mode=True):
                l_nd = loss_blk(*[NDArray(o) for o in outs],
                                NDArray(labels))
            return jnp.mean(l_nd._data)

        return stage_fn, mb_loss

    def _build_step_1f1b(self):
        """The 1F1B schedule: each tick runs ONE forward and ONE backward
        microbatch per stage, with the backward written out explicitly
        (per-stage ``jax.vjp`` + manual cotangent hops) instead of
        differentiating through the whole forward scan.

        Why it exists: under ``jax.grad``-over-scan (the GPipe path),
        every tick's residuals are saved for the transpose — peak
        activation memory grows with the microbatch count M.  Here the
        only activation state is a circular stash of the last 2S stage
        INPUTS (the forward is recomputed inside each stage's vjp —
        remat-style), so peak memory is O(S), independent of M: raising
        M to shrink the bubble no longer costs memory.

        Timing: stage s forwards microbatch f at tick s + f and backwards
        microbatch b at tick (2S - 1 - s) + b — the classic 1F1B offsets;
        in-flight activations per stage = 2(S - s) - 1 <= 2S - 1 (hence
        the 2S stash).  Total ticks M + 2S - 1 covering forward AND
        backward, vs GPipe's (M + S - 1) forward ticks plus the same
        again in the AD-generated reverse sweep.

        Equivalence: identical math to GPipe, reordered — the trainer
        test proves loss-parity against the 1-device oracle for both
        schedules.  (Reference analog: none — SURVEY §2.4; PipeDream/
        Megatron 1F1B re-derived for the SPMD single-program form.)"""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ._shmap import shard_map

        mesh, S, M = self._mesh, self._S, self._M
        pipe, data = self._pipe_axis, self._data_axis
        first_fn = self._first_fn
        stage_fn, mb_loss = self._stage_closures()
        D = 2 * S                       # stash depth >= max in-flight

        def body(fv, sv, lv, ids_l, labels_l):
            stage = jax.lax.axis_index(pipe)
            p_stage = jax.tree.map(lambda a: a[0], sv)
            b_l = ids_l.shape[0]
            ids_mb = ids_l.reshape(M, b_l // M, *ids_l.shape[1:])
            labels_mb = labels_l.reshape(M, b_l // M,
                                         *labels_l.shape[1:])
            x0_shape = jax.eval_shape(first_fn, fv, ids_mb[0])
            zx = jnp.zeros(x0_shape.shape, x0_shape.dtype)
            stash0 = jnp.zeros((D,) + x0_shape.shape, x0_shape.dtype)

            def tick(carry, t):
                (stash, f_buf, b_buf, g_sv, g_fv, g_lv,
                 loss_acc) = carry
                # ---- forward lane: microbatch t - stage
                f_mb = t - stage
                f_ok = (f_mb >= 0) & (f_mb < M)
                f_idx = jnp.clip(f_mb, 0, M - 1)
                x0 = first_fn(fv, ids_mb[f_idx])
                in_f = jnp.where(stage == 0, x0, f_buf)
                out_f = stage_fn(p_stage, in_f)
                slot_f = f_idx % D
                stash = stash.at[slot_f].set(
                    jnp.where(f_ok, in_f, stash[slot_f]))
                # ---- backward lane: microbatch t - (2S - 1 - stage)
                b_mb = t - (2 * S - 1 - stage)
                b_ok = (b_mb >= 0) & (b_mb < M)
                b_idx = jnp.clip(b_mb, 0, M - 1)
                x_in = stash[b_idx % D]
                out_b, stage_vjp = jax.vjp(stage_fn, p_stage, x_in)
                lb = labels_mb[b_idx]
                loss_b, (g_lv_h, g_fv_h, cot_head) = jax.value_and_grad(
                    lambda a: mb_loss(a[0], a[1], a[2], lb))(
                        (lv, fv, out_b))
                is_last = stage == S - 1
                cot_out = jnp.where(is_last, cot_head, b_buf)
                g_p_inc, d_in = stage_vjp(cot_out)
                # stage-0 embed backward chains the returned input
                # cotangent into first_fn's params (tied-head grads for
                # fv come from the head vjp on the last stage; both
                # contributions accumulate, psum'd over pipe after)
                _, emb_vjp = jax.vjp(
                    lambda f: first_fn(f, ids_mb[b_idx]), fv)
                (g_fv_e,) = emb_vjp(d_in)

                def acc(ok):
                    return lambda g, inc: g + jnp.where(
                        ok, inc, jnp.zeros_like(inc))
                g_sv = jax.tree.map(acc(b_ok), g_sv, g_p_inc)
                g_lv = jax.tree.map(acc(b_ok & is_last), g_lv, g_lv_h)
                g_fv = jax.tree.map(acc(b_ok & is_last), g_fv, g_fv_h)
                g_fv = jax.tree.map(acc(b_ok & (stage == 0)),
                                    g_fv, g_fv_e)
                loss_acc = loss_acc + jnp.where(b_ok & is_last,
                                                loss_b, 0.0)
                f_nxt = jax.lax.ppermute(
                    out_f, pipe, [(i, (i + 1) % S) for i in range(S)])
                b_nxt = jax.lax.ppermute(
                    d_in, pipe, [(i, (i - 1) % S) for i in range(S)])
                return (stash, f_nxt, b_nxt, g_sv, g_fv, g_lv,
                        loss_acc), None

            carry0 = (stash0, zx, zx,
                      jax.tree.map(jnp.zeros_like, p_stage),
                      jax.tree.map(jnp.zeros_like, fv),
                      jax.tree.map(jnp.zeros_like, lv),
                      jnp.zeros((), jnp.float32))
            (_, _, _, g_sv, g_fv, g_lv, loss_acc), _ = jax.lax.scan(
                tick, carry0, jnp.arange(M + 2 * S - 1))
            # mean over microbatches (the GPipe objective) + data axis;
            # fv/lv contributions live on stages 0 / S-1 -> psum(pipe)
            loss = jax.lax.pmean(jax.lax.psum(loss_acc / M, pipe), data)
            g_fv = jax.tree.map(
                lambda g: jax.lax.pmean(jax.lax.psum(g / M, pipe), data),
                g_fv)
            g_lv = jax.tree.map(
                lambda g: jax.lax.pmean(jax.lax.psum(g / M, pipe), data),
                g_lv)
            g_sv = jax.tree.map(
                lambda g: jax.lax.pmean(g / M, data)[None], g_sv)
            return loss, g_fv, g_sv, g_lv

        fv_specs = jax.tree.map(lambda _: P(), self._first_vals)
        lv_specs = jax.tree.map(lambda _: P(), self._last_vals)
        sv_specs = pipe_specs(self._stacked, pipe)

        def batch_spec(x):
            return P(data, *([None] * (x.ndim - 1)))

        opt = self._opt

        def pure_step(fv, sv, lv, opt_state, step, ids, labels):
            sharded = shard_map(
                body, mesh=mesh,
                in_specs=(fv_specs, sv_specs, lv_specs,
                          batch_spec(ids), batch_spec(labels)),
                out_specs=(P(), fv_specs, sv_specs, lv_specs),
                check_vma=False,
                # data/pipe are MANUAL (the schedule psums over them);
                # every other mesh axis (e.g. a tensor-parallel 'model')
                # stays AUTO — GSPMD shards the stage matmuls over it
                # from the parameter shardings alone (3D parallelism)
                axis_names=frozenset({data, pipe}))
            loss, g_fv, g_sv, g_lv = sharded(fv, sv, lv, ids, labels)
            (nf, ns, nl), nstate = opt.update(
                (fv, sv, lv), (g_fv, g_sv, g_lv), opt_state, step)
            return loss, nf, ns, nl, nstate

        donate = (0, 1, 2, 3) if self._donate else ()
        fv_sh = tuple(v.sharding for v in self._first_vals)
        lv_sh = tuple(v.sharding for v in self._last_vals)
        sv_sh = {k: v.sharding for k, v in self._stacked.items()}
        from .. import telemetry as _telemetry
        return _telemetry.instrument_jit(
            "pipeline:1f1b",
            jax.jit(pure_step,
                    out_shardings=(None, fv_sh, sv_sh, lv_sh, None),
                    donate_argnums=donate))

    def _build_step_gpipe(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ._shmap import shard_map

        mesh, S, M = self._mesh, self._S, self._M
        pipe, data = self._pipe_axis, self._data_axis
        first_fn = self._first_fn
        stage_fn, mb_loss = self._stage_closures()

        def body(fv, sv, lv, ids_l, labels_l):
            stage = jax.lax.axis_index(pipe)
            p_stage = jax.tree.map(lambda a: a[0], sv)
            b_l = ids_l.shape[0]
            ids_mb = ids_l.reshape(M, b_l // M, *ids_l.shape[1:])
            labels_mb = labels_l.reshape(M, b_l // M,
                                         *labels_l.shape[1:])
            x0_shape = jax.eval_shape(first_fn, fv, ids_mb[0])
            buf = jnp.zeros(x0_shape.shape, x0_shape.dtype)
            losses0 = jnp.zeros((M,), jnp.float32)

            def tick(carry, t):
                buf, losses = carry
                mb_in = jnp.clip(t, 0, M - 1)
                # non-0 stages compute-and-discard the embed (the price
                # of single-program SPMD form; see class docstring)
                x0 = first_fn(fv, ids_mb[mb_in])
                inp = jnp.where(stage == 0, x0, buf)
                out = stage_fn(p_stage, inp)
                idx = jnp.clip(t - stage, 0, M - 1)
                loss_t = mb_loss(lv, fv, out, labels_mb[idx])
                valid = ((stage == S - 1) & (t >= stage)
                         & (t < stage + M))
                losses = losses.at[idx].set(
                    jnp.where(valid, loss_t, losses[idx]))
                nxt = jax.lax.ppermute(
                    out, pipe, [(i, (i + 1) % S) for i in range(S)])
                return (nxt, losses), None

            (_, losses), _ = jax.lax.scan(
                tick, (buf, losses0), jnp.arange(M + S - 1))
            # only the last stage wrote real losses; psum replicates
            loss = jax.lax.psum(jnp.sum(losses) / M, pipe)
            return jax.lax.pmean(loss, data)

        fv_specs = jax.tree.map(lambda _: P(), self._first_vals)
        lv_specs = jax.tree.map(lambda _: P(), self._last_vals)
        sv_specs = pipe_specs(self._stacked, pipe)

        def batch_spec(x):
            return P(data, *([None] * (x.ndim - 1)))

        opt = self._opt

        def pure_step(fv, sv, lv, opt_state, step, ids, labels):
            sharded = shard_map(
                body, mesh=mesh,
                in_specs=(fv_specs, sv_specs, lv_specs,
                          batch_spec(ids), batch_spec(labels)),
                out_specs=P(), check_vma=False,
                # see _build_step_1f1b: non-data/pipe axes stay auto
                axis_names=frozenset({data, pipe}))

            def loss_of(tr):
                f, s, l = tr
                return sharded(f, s, l, ids, labels)

            loss, grads = jax.value_and_grad(loss_of)((fv, sv, lv))
            (nf, ns, nl), nstate = opt.update((fv, sv, lv), grads,
                                              opt_state, step)
            return loss, nf, ns, nl, nstate

        donate = (0, 1, 2, 3) if self._donate else ()
        fv_sh = tuple(v.sharding for v in self._first_vals)
        lv_sh = tuple(v.sharding for v in self._last_vals)
        sv_sh = {k: v.sharding for k, v in self._stacked.items()}
        from .. import telemetry as _telemetry
        return _telemetry.instrument_jit(
            "pipeline:gpipe",
            jax.jit(pure_step,
                    out_shardings=(None, fv_sh, sv_sh, lv_sh, None),
                    donate_argnums=donate))

    def step(self, *batch):
        """One pipelined train step (ids, labels); returns the scalar
        loss (replicated, async)."""
        import jax.numpy as jnp
        ids, labels = batch
        sharded = tuple(self._shard_batch(b) for b in batch)
        dp = self._mesh.shape[self._data_axis]
        b_local = sharded[0].shape[0] // dp
        if sharded[0].shape[0] % dp or b_local % self._M:
            raise MXNetError(
                f"global batch {sharded[0].shape[0]} must split over "
                f"data axis {dp} x microbatches {self._M}")
        cache_key = tuple((a.shape, str(a.dtype)) for a in sharded)
        if cache_key not in self._jit_cache:
            self._jit_cache[cache_key] = self._build_step()
        self._step_count += 1
        step_arr = jnp.asarray(self._step_count, jnp.int32)
        (loss, self._first_vals, self._stacked, self._last_vals,
         self._opt_state) = self._jit_cache[cache_key](
            self._first_vals, self._stacked, self._last_vals,
            self._opt_state, step_arr, *sharded)
        return loss

    def sync_to_block(self):
        """Write trained values back into the net's Parameters (cell
        leaves unstacked to their per-stage owners; multi-host shards
        allgathered first, like SPMDTrainer.sync_to_block)."""
        import jax
        from .spmd import _fetch_full
        for p, v in zip(
                list(self._first_params) + list(self._last_params),
                list(self._first_vals) + list(self._last_vals)):
            dev = p.data().ctx.jax_device()
            p._data._set_data(jax.device_put(_fetch_full(v), dev))
        L, S = self._L, self._S
        for j in range(L):
            for i in range(len(self._cell_trainables[0])):
                leaf = _fetch_full(self._stacked[f"c{j}_p{i}"])
                for s in range(S):
                    p = self._cell_trainables[s * L + j][i]
                    dev = p.data().ctx.jax_device()
                    p._data._set_data(jax.device_put(leaf[s], dev))
