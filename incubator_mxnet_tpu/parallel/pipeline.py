"""Pipeline parallelism: a GPipe schedule as ONE SPMD program
(reference analog: the reference had no pipeline engine — its
distributed story was data parallelism over kvstore; this is the
beyond-parity axis completing dp/tp/sp/ep/pp.  Pattern: the
pipelined-scan recipe of the TPU scaling playbook — stack homogeneous
stage parameters, shard the stack over a mesh axis, stream microbatches
around the ring with ppermute inside lax.scan).

Design:
  * stage parameters are STACKED pytrees — every leaf (S, ...) — and
    sharded over the ``pipe`` mesh axis, so placement is a
    PartitionSpec, exactly like tensor/expert parallelism here;
  * the schedule runs M + S - 1 ticks; every device runs the SAME
    program each tick (SPMD — idle bubble ticks compute on garbage and
    are masked), activations hop stage->stage+1 via ppermute over ICI;
  * differentiable end to end: lax.scan + ppermute transpose cleanly,
    so jax.grad/SPMDTrainer-style training through the pipeline needs
    nothing special;
  * microbatches enter replicated; outputs are collected on the last
    stage and replicated back with a psum — callers see a plain
    (M, ...) array.
"""
from __future__ import annotations

from typing import Any, Callable

from ..base import MXNetError

__all__ = ["gpipe", "stack_stage_params", "pipe_specs",
           "stack_block_stages"]


def stack_block_stages(blocks, training=False, rng_key=None):
    """Turn a list of same-architecture (initialized, shape-settled)
    Blocks into pipeline stages: returns ``(stage_fn, stacked_params)``
    for :func:`gpipe`.  The first block is the template whose forward
    runs functionally with each stage's parameter values substituted —
    the ONE place the cell-as-stage recipe lives (used by the driver
    dryrun and the tests alike).

    ``training`` selects the train-mode forward.  Stage calls are pure
    fn(params, x): STOCHASTIC layers would get the one ``rng_key`` on
    every call and AUXILIARY state (BatchNorm running stats) has no way
    out of the schedule — so training=True REFUSES blocks with active
    Dropout or aux state rather than silently mis-sampling/stale-ing
    them.  Build pipelined stages from deterministic, stateless layers
    (LayerNorm etc.), the standard pipeline practice."""
    import jax
    from ..gluon.block import functional_call
    from ..ndarray.ndarray import NDArray
    if not blocks:
        raise MXNetError("stack_block_stages needs >= 1 block")
    template = blocks[0]
    if training:
        from ..gluon import nn as _nn
        drops = []
        template.apply(lambda b: drops.append(b)
                       if isinstance(b, _nn.Dropout)
                       and getattr(b, "_rate", 0) else None)
        if drops:
            raise MXNetError(
                "stack_block_stages(training=True) with active Dropout: "
                "the pure stage contract would reuse one RNG key for "
                "every stage/microbatch — build the stages with "
                "dropout=0 instead")
    trainable = list(template.collect_params().values())
    if any(p.grad_req == "null" for p in trainable) and training:
        raise MXNetError(
            "stack_block_stages(training=True) with auxiliary state "
            "(BatchNorm running stats): the pure stage contract cannot "
            "carry aux updates out of the schedule — use stateless "
            "normalization (LayerNorm/GroupNorm) in pipelined stages")
    # readable keys: strip the template's own prefix; stages align by
    # POSITION (collect_params order is construction order, identical
    # for same-architecture blocks), so a key collision — possible with
    # prefix='' where child names carry no shared block prefix — falls
    # back to enumerated keys rather than silently merging params
    pfx = getattr(template, "prefix", "") or ""
    names = [p.name[len(pfx):] if pfx and p.name.startswith(pfx)
             else p.name for p in trainable]
    if len(set(names)) != len(names):
        names = [f"p{i}_{n}" for i, n in enumerate(names)]
    trees = []
    for b in blocks:
        ps = list(b.collect_params().values())
        if len(ps) != len(names):
            raise MXNetError("stage blocks differ in parameter count")
        trees.append({n: p.data()._data for n, p in zip(names, ps)})
    stacked = stack_stage_params(trees)
    key = rng_key if rng_key is not None else jax.random.PRNGKey(0)

    def stage_fn(p, x):
        outs, _ = functional_call(template, trainable,
                                  [p[n] for n in names], [], [],
                                  [NDArray(x)], training, key)
        return outs[0]

    return stage_fn, stacked


def stack_stage_params(param_trees):
    """Stack per-stage parameter pytrees (a list of S same-structure
    trees) into one tree whose leaves carry a leading stage axis."""
    import jax
    import jax.numpy as jnp
    if not param_trees:
        raise MXNetError("stack_stage_params needs >= 1 stage tree")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_trees)


def pipe_specs(stacked_params, axis="pipe"):
    """PartitionSpecs sharding every leaf's leading (stage) axis."""
    import jax
    from jax.sharding import PartitionSpec as P

    def leaf(v):
        return P(axis, *([None] * (v.ndim - 1)))
    return jax.tree.map(leaf, stacked_params)


def gpipe(stage_fn: Callable[[Any, Any], Any], stacked_params, xs,
          mesh, axis: str = "pipe"):
    """Apply S pipeline stages to M microbatches.

    stage_fn(params, x) -> y : one stage's computation (same shape in
    and out — the transformer-layer contract); ``stacked_params``:
    pytree with leading stage dim S == mesh.shape[axis];
    ``xs``: (M, ...) microbatched activations.  Returns (M, ...) — the
    composition stage_{S-1}(...stage_0(x)) per microbatch, replicated.

    Wall-clock is (M + S - 1)/M of the ideal — the GPipe bubble; raise
    M to amortize.  Gradients flow through (scan + ppermute transpose).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    if axis not in mesh.shape:
        raise MXNetError(f"mesh has no axis {axis!r}")
    S = mesh.shape[axis]
    M = xs.shape[0]
    leading = {v.shape[0] for v in jax.tree.leaves(stacked_params)}
    if leading != {S}:
        raise MXNetError(
            f"stacked_params leading dims {sorted(leading)} != pipe "
            f"axis size {S}")

    def body(params_local, xs_rep):
        stage = jax.lax.axis_index(axis)
        p = jax.tree.map(lambda a: a[0], params_local)  # this stage's
        buf = jnp.zeros_like(xs_rep[0])
        ys0 = jnp.zeros_like(xs_rep)

        def tick(carry, t):
            buf, ys = carry
            # stage 0 ingests microbatch t (clipped reads during the
            # drain phase are masked out downstream)
            inp = jnp.where(stage == 0,
                            xs_rep[jnp.clip(t, 0, M - 1)], buf)
            out = stage_fn(p, inp)
            # the last stage owns microbatch t - stage at this tick
            idx = jnp.clip(t - stage, 0, M - 1)
            valid = (stage == S - 1) & (t >= stage) & (t < stage + M)
            ys = ys.at[idx].set(jnp.where(valid, out, ys[idx]))
            nxt = jax.lax.ppermute(
                out, axis, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, ys), None

        (_, ys), _ = jax.lax.scan(tick, (buf, ys0),
                                  jnp.arange(M + S - 1))
        # only the last stage holds real outputs; psum replicates them
        ys = jnp.where(stage == S - 1, ys, jnp.zeros_like(ys))
        return jax.lax.psum(ys, axis)

    in_specs = (pipe_specs(stacked_params, axis), P())
    return shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=P(),
                     check_vma=False)(stacked_params, xs)
