"""Multi-host bootstrap (reference: the DMLC_* env-var topology of ps-lite —
3rdparty/ps-lite van.cc, tools/launch.py — re-mapped onto
``jax.distributed``).

One process per host; after ``initialize()``, ``jax.devices()`` spans the
pod and a Mesh built from it gives DP/TP/SP over ICI+DCN.  Reference env
vars are honored so reference launch scripts keep working:

  DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT -> coordinator address
  DMLC_NUM_WORKER                      -> num_processes
  DMLC_WORKER_ID (or DMLC_RANK)        -> process_id
"""
from __future__ import annotations

import os
from typing import Optional

from ..base import MXNetError

__all__ = ["initialize", "shutdown", "rank", "num_workers",
           "local_device_count", "global_device_count", "barrier"]

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None):
    """Connect this process to the job (reference analog: ps-lite Van
    connect to DMLC_PS_ROOT_URI + barrier)."""
    global _initialized
    import jax
    if _initialized:
        return
    if coordinator_address is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        nw = os.environ.get("DMLC_NUM_WORKER")
        num_processes = int(nw) if nw else None
    if process_id is None:
        pid = os.environ.get("DMLC_WORKER_ID", os.environ.get("DMLC_RANK"))
        process_id = int(pid) if pid else None
    if coordinator_address is None and num_processes in (None, 1):
        _initialized = True  # single-host: nothing to do
        return
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id,
                               local_device_ids=local_device_ids)
    _initialized = True


def shutdown():
    global _initialized
    import jax
    if _initialized:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
        _initialized = False


def rank() -> int:
    import jax
    return jax.process_index()


def num_workers() -> int:
    import jax
    return jax.process_count()


def local_device_count() -> int:
    import jax
    return jax.local_device_count()


def global_device_count() -> int:
    import jax
    return jax.device_count()


def barrier(name: str = "barrier"):
    """Block until all processes arrive (reference: ps Postoffice barrier).
    Implemented as a tiny psum across the global mesh."""
    import jax
    import jax.numpy as jnp
    if jax.process_count() == 1:
        return
    from jax.sharding import NamedSharding, PartitionSpec, Mesh
    import numpy as np
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("all",))
    x = jnp.zeros(len(devs))
    xs = jax.device_put(x, NamedSharding(mesh, PartitionSpec("all")))
    jnp.sum(xs).block_until_ready()
