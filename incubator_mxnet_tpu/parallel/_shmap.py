"""Version-tolerant ``shard_map``.

Call sites in this package write the modern API — ``jax.shard_map(f,
mesh=..., in_specs=..., out_specs=..., check_vma=..., axis_names=...)``.
jax 0.4.x only ships ``jax.experimental.shard_map.shard_map`` whose
equivalents are spelled ``check_rep`` and ``auto`` (the COMPLEMENT of
``axis_names``: axes left automatic instead of axes made manual), so
this wrapper translates the kwargs instead of forking every call site.
"""
from __future__ import annotations

from typing import Any, Optional


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: bool = True,
              axis_names: Optional[Any] = None):
    try:
        from jax import shard_map as _sm
    except ImportError:
        _sm = None
    if _sm is not None:
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return _sm(f, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _esm
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=bool(check_vma), auto=auto)
