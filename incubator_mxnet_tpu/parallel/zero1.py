"""ZeRO-1 weight-update sharding (arXiv:2004.13336).

Data-parallel training replicates optimizer state and redundantly
computes the whole-tree update on every replica.  The paper's scheme —
stage 1 of ZeRO — partitions the *update* instead: each replica owns a
contiguous 1/N shard of the flattened parameter space, updates only its
shard of the weights and optimizer state, and an all-gather rebuilds the
full weights for the next forward pass.  The gradient all-reduce
decomposes into reduce-scatter (each replica receives the summed grads
for its shard) + all-gather (of updated weights), so per-replica
optimizer-state memory drops N× for the price of one weights-worth of
gather traffic per step.

This module provides the layout bookkeeping and the functional wrapper:

* :class:`ShardSpec` — the contiguous-slice layout of a fixed list of
  leaves flattened into one (or a few, grouped by a static key) 1-D
  buffers, each padded to a multiple of ``n_shards``.
* :func:`flatten_segment` / :func:`unflatten_segment` — pure ``jnp``
  transforms usable both in-program (traced) and eagerly.
* :class:`Zero1Optimizer` — wraps a ``parallel.optim``
  FunctionalOptimizer so its state lives as dp-sharded flat buffers and
  its update runs on the local shard only, with the weight all-gather
  expressed as a sharding constraint INSIDE the program — the whole
  thing stays within the single donated dispatch of ``SPMDTrainer``'s
  step and ``CompiledLoop``'s k-step scan.

The sharding is expressed with GSPMD constraints
(``lax.with_sharding_constraint`` on the flat buffers + ``out_shardings``
pinning the state to ``P(axis)``) rather than ``shard_map``: the
elementwise update cores need no index plumbing, and XLA places the
reduce-scatter / all-gather around the constrained region.  Because the
supported cores (sgd / momentum / nag / adam / adamw / rmsprop /
adagrad) are purely elementwise, the sharded update is bit-identical to
the replicated one; rules with per-tensor reductions (LAMB's trust
ratio) straddle shard boundaries and are excluded
(``FunctionalOptimizer.elementwise`` is False → callers fall back).
"""
from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["Segment", "ShardSpec", "build_shard_spec", "flatten_segment",
           "unflatten_segment", "expand_per_leaf", "Zero1Optimizer",
           "per_replica_state_bytes"]


class Segment(NamedTuple):
    """One flat buffer: a run of leaves sharing a static key (dtype, and
    for the fused tier wd/multi-precision pattern), laid out back to
    back and zero-padded so ``padded % n_shards == 0``."""
    key: Any
    idx: Tuple[int, ...]          # positions in the original leaf list
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    dtype: Any                    # numpy dtype of the flat buffer
    total: int                    # sum(sizes)
    padded: int                   # total rounded up to n_shards multiple


class ShardSpec(NamedTuple):
    """Contiguous-slice layout of a fixed leaf list across ``n_shards``
    data-parallel shards.  Records enough to round-trip
    leaves <-> flat padded segments on host or in-program, and to
    re-partition a checkpoint saved at a different shard count."""
    n_shards: int
    n_leaves: int
    segments: Tuple[Segment, ...]


def _np():
    import numpy as np
    return np


def build_shard_spec(leaves, n_shards: int, keys=None) -> ShardSpec:
    """Group ``leaves`` (arrays or ShapeDtypeStructs) by ``keys``
    (default: dtype) preserving order within each group, and record the
    flat padded layout.  ``n_shards`` must be >= 1; padding makes every
    segment length divisible by it so a 1-D ``P(axis)`` sharding is
    always legal."""
    np = _np()
    if n_shards < 1:
        raise MXNetError(f"n_shards must be >= 1, got {n_shards}")
    leaves = list(leaves)
    if keys is None:
        keys = [np.dtype(x.dtype).str for x in leaves]
    if len(keys) != len(leaves):
        raise MXNetError("build_shard_spec: len(keys) != len(leaves)")
    order: List[Any] = []
    groups: dict = {}
    for i, (leaf, key) in enumerate(zip(leaves, keys)):
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)
    segments = []
    for key in order:
        idx = tuple(groups[key])
        shapes = tuple(tuple(int(d) for d in leaves[i].shape) for i in idx)
        sizes = tuple(int(np.prod(s, dtype=np.int64)) if s else 1
                      for s in shapes)
        offsets, off = [], 0
        for s in sizes:
            offsets.append(off)
            off += s
        total = off
        padded = total + (-total) % n_shards if total else n_shards
        segments.append(Segment(
            key=key, idx=idx, shapes=shapes, sizes=sizes,
            offsets=tuple(offsets), dtype=np.dtype(leaves[idx[0]].dtype),
            total=total, padded=padded))
    return ShardSpec(n_shards=int(n_shards), n_leaves=len(leaves),
                     segments=tuple(segments))


def flatten_segment(seg: Segment, leaves, dtype=None):
    """Concatenate the segment's leaves (raveled, optionally cast) into
    one zero-padded 1-D buffer.  Pure jnp — traceable."""
    import jax.numpy as jnp
    dt = dtype or seg.dtype
    parts = [jnp.ravel(leaves[i]).astype(dt) for i in seg.idx]
    pad = seg.padded - seg.total
    if pad or not parts:
        parts.append(jnp.zeros((pad if parts else seg.padded,), dt))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def unflatten_segment(seg: Segment, flat):
    """Inverse of :func:`flatten_segment` (padding dropped): returns
    ``[(leaf_index, array), ...]`` in segment order.  Pure jnp."""
    out = []
    for i, shape, size, off in zip(seg.idx, seg.shapes, seg.sizes,
                                   seg.offsets):
        out.append((i, flat[off:off + size].reshape(shape)))
    return out


def expand_per_leaf(seg: Segment, values, dtype=None):
    """Per-leaf scalars → flat vector constant over each leaf's slice
    (zeros in the padding).  ``values`` indexes the ORIGINAL leaf list;
    elementwise-multiplying the result is bit-identical to broadcasting
    each scalar over its own leaf.  Pure jnp — traceable."""
    import jax.numpy as jnp
    dt = dtype or seg.dtype
    parts = [jnp.broadcast_to(values[i].astype(dt), (size,))
             for i, size in zip(seg.idx, seg.sizes)]
    pad = seg.padded - seg.total
    if pad or not parts:
        parts.append(jnp.zeros((pad if parts else seg.padded,), dt))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def flatten_tree(spec: ShardSpec, leaves):
    """All segments of ``leaves`` as a tuple of flat padded buffers."""
    return tuple(flatten_segment(seg, leaves) for seg in spec.segments)


def unflatten_tree(spec: ShardSpec, flats):
    """Inverse of :func:`flatten_tree`: tuple of leaves in original
    order."""
    out: List[Any] = [None] * spec.n_leaves
    for seg, flat in zip(spec.segments, flats):
        for i, arr in unflatten_segment(seg, flat):
            out[i] = arr
    return tuple(out)


def per_replica_state_bytes(tree) -> int:
    """Bytes of optimizer state ONE replica materializes: each leaf's
    per-device shard shape (full shape when unsharded/eager) times its
    itemsize — the feed for the ``mxtpu_optimizer_state_bytes`` gauge."""
    import jax
    import numpy as np
    total = 0
    for leaf in jax.tree.leaves(tree):
        if leaf is None:
            continue
        shape = tuple(leaf.shape)
        sh = getattr(leaf, "sharding", None)
        if sh is not None:
            try:
                shape = sh.shard_shape(shape)
            except Exception:
                pass
        total += int(np.prod(shape, dtype=np.int64)) * \
            np.dtype(leaf.dtype).itemsize
    return total


def zero1_allgather_bytes(spec: ShardSpec) -> int:
    """Per-step, per-replica inbound all-gather volume the scheme adds:
    every replica receives the other N-1 shards of each flat weight
    buffer after the sharded update."""
    np = _np()
    n = spec.n_shards
    total = 0
    for seg in spec.segments:
        total += seg.padded * np.dtype(seg.dtype).itemsize
    return total * (n - 1) // n


class Zero1Optimizer:
    """ZeRO-1 wrapper around a ``parallel.optim`` FunctionalOptimizer.

    Duck-types the ``(init, update)`` pair SPMDTrainer / CompiledLoop
    drive, but:

    * ``init`` flattens the params into per-dtype padded segments and
      places the base optimizer's state — whose leaves are now those
      flat buffers — with ``NamedSharding(mesh, P(axis))``, so each
      replica holds 1/N of every state buffer;
    * ``update`` flattens params and grads IN-PROGRAM, pins them to
      ``P(axis)`` (the slice is free under GSPMD; with a preceding
      psum the compiler fuses it into a reduce-scatter), runs the base
      update on the flat tree, re-pins the new state to ``P(axis)`` and
      the new flat weights to replicated — the all-gather — then
      unflattens.  No host round-trip: callers' donated single dispatch
      is preserved.

    The portable_state / from_portable pair converts between the flat
    sharded layout and the plain per-leaf layout the unsharded tier
    uses, making checkpoints independent of the shard count (save at
    N=8, resume at N=4) and interchangeable with non-ZeRO trainers.
    """

    def __init__(self, base, mesh, axis: str = "data"):
        if not getattr(base, "elementwise", True):
            raise MXNetError(
                "zero1: optimizer update is not elementwise (per-tensor "
                "reductions straddle shard boundaries) — use the "
                "unsharded path")
        self.base = base
        self.mesh = mesh
        self.axis = axis
        self.spec: Optional[ShardSpec] = None
        self.n_shards = int(mesh.shape[axis])

    # -- sharding helpers ----------------------------------------------
    def _sharded(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec(self.axis))

    def _replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(self.mesh, PartitionSpec())

    def _map_flats(self, state, fn):
        """Apply ``fn`` to every flat buffer in the state.  The base
        optimizers all return ``{name: params-shaped tree}`` where the
        params tree here is the tuple of flat segments."""
        import jax
        return jax.tree.map(fn, state)

    # -- FunctionalOptimizer surface -----------------------------------
    def init(self, params):
        import jax
        leaves = jax.tree.leaves(params)
        self.spec = build_shard_spec(leaves, self.n_shards)
        flats = flatten_tree(self.spec, leaves)
        state = self.base.init(flats)
        shard = self._sharded()
        return self._map_flats(state, lambda v: jax.device_put(v, shard))

    def update(self, params, grads, state, step):
        import jax
        from jax.lax import with_sharding_constraint as wsc
        if self.spec is None:
            raise MXNetError("zero1: update before init")
        spec = self.spec
        p_leaves, treedef = jax.tree.flatten(params)
        g_leaves = jax.tree.leaves(grads)
        shard, repl = self._sharded(), self._replicated()
        flat_p = tuple(wsc(f, shard) for f in flatten_tree(spec, p_leaves))
        flat_g = tuple(wsc(f, shard) for f in flatten_tree(spec, g_leaves))
        new_fp, new_state = self.base.update(flat_p, flat_g, state, step)
        new_state = self._map_flats(new_state, lambda v: wsc(v, shard))
        # the all-gather: replicating the updated flat weights is the
        # only cross-replica traffic the scheme adds.  The barrier keeps
        # the update arithmetic out of the all-gather's fusion cluster —
        # fused in, XLA re-contracts the multiply-add chains (FMA
        # placement changes) and results drift 1-2 ulp off the unsharded
        # program; the kernel boundary preserves bit parity.
        new_fp = tuple(wsc(jax.lax.optimization_barrier(f), repl)
                       for f in new_fp)
        new_leaves = unflatten_tree(spec, new_fp)
        return jax.tree.unflatten(treedef, new_leaves), new_state

    # -- state layout conversions --------------------------------------
    def state_shardings(self, state):
        sh = self._sharded()
        return self._map_flats(state, lambda v: sh)

    def portable_state(self, state, fetch=None):
        """Sharded flat state → host numpy state with the SAME structure
        the unsharded functional tier produces ({name: per-leaf tuple}),
        so checkpoints are shard-count-agnostic."""
        import numpy as np
        if fetch is None:
            fetch = lambda v: np.asarray(v)         # noqa: E731
        spec = self.spec

        def to_leaves(flats):
            flats = tuple(fetch(f) for f in flats)
            return unflatten_tree(spec, flats)
        return {k: to_leaves(v) for k, v in state.items()}

    def from_portable(self, state):
        """Per-leaf state (from :meth:`portable_state`, possibly saved
        at a DIFFERENT shard count, or from an unsharded trainer) →
        flat buffers placed with the current mesh's sharding."""
        import jax
        shard = self._sharded()

        def to_flats(leaves):
            flats = flatten_tree(self.spec, list(leaves))
            return tuple(jax.device_put(f, shard) for f in flats)
        return {k: to_flats(v) for k, v in state.items()}
