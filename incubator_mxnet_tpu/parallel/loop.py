"""CompiledLoop: k train steps captured as ONE donated XLA program.

A per-step trainer (SPMDTrainer, or the eager Trainer with the fused
optimizer) still pays several host round-trips per step: batch placement,
forward/backward dispatch, optimizer dispatch, loss readback.  PyGraph
(PAPERS.md, arXiv:2503.19779) shows that capturing the FULL iteration —
not just its kernels — is where the remaining launch overhead goes.
``CompiledLoop`` does that capture with ``lax.scan``:

* loss + grad + functional optimizer update for ``k`` consecutive steps
  trace into one jit program (``donate_argnums=(0, 1, 2)``), so a k-step
  chunk is a SINGLE dispatch;
* lr/wd schedules receive the traced per-inner-step counter, so warmup /
  decay curves are exact inside the chunk, not frozen at its boundary;
* the per-step host RNG keys are stacked into the scan's xs — a chunk
  consumes the IDENTICAL ``random.new_key()`` stream as k separate
  ``SPMDTrainer.step`` calls, which is what makes chunking invariant
  (bit-identical params for any k) and mid-chunk resume possible;
* with ``skip_nonfinite=True`` the non-finite guard (PR 3/4 semantics)
  runs INSIDE the scan: a step whose gradients contain NaN/Inf leaves
  params and optimizer state untouched, and a device-side skipped-step
  counter is surfaced once per chunk — drained asynchronously, published
  as FAULT ``skipped_step`` events, never a host sync on the hot path.

Pair with :class:`~incubator_mxnet_tpu.io.prefetch.DevicePrefetcher`
(``run(..., prefetch=True)`` does it for you) so fetch + h2d of batch
i+1 overlap compute of batch i; the host then blocks only at epoch and
checkpoint boundaries.

Checkpoint/resume: ``get_states``/``set_states`` round-trip the step
counter, skipped-step count and optimizer state through
``AsyncCheckpointer`` exactly like the eager Trainer, and the manifest's
RNG snapshot keeps the key stream aligned, so a run checkpointed
mid-chunk (say step 6 of k=4 chunks) resumes bit-identically.
"""
from __future__ import annotations

import pickle
import time as _time

from ..base import MXNetError, getenv_int
from .. import health as _health
from .. import telemetry as _telemetry
from .spmd import SPMDTrainer, _fetch_full, _placed_copy

__all__ = ["CompiledLoop"]


class CompiledLoop(SPMDTrainer):
    """Scan ``loop_steps`` train steps into one donated program.

    Usage::

        loop = CompiledLoop(net, loss_fn, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            loop_steps=8, mesh=mesh)
        losses = loop.run(loader)       # prefetch + chunked dispatch
        loop.sync_to_block()

    Or drive chunks by hand with :meth:`step_chunk`.  ``step`` (inherited)
    still works and stays bit-compatible: a k-chunk equals k single steps.
    """

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 loop_steps=None, skip_nonfinite=False, **kwargs):
        super().__init__(net, loss_fn, optimizer, optimizer_params,
                         **kwargs)
        if self._accum != 1:
            raise MXNetError(
                "CompiledLoop does not compose with accum_steps yet — "
                "fold the accumulation into loop_steps instead")
        self.loop_steps = int(loop_steps) if loop_steps is not None \
            else getenv_int("MXNET_LOOP_STEPS", 8)
        if self.loop_steps < 1:
            raise MXNetError(
                f"loop_steps={self.loop_steps} must be >= 1")
        self._skip_nonfinite = bool(skip_nonfinite)
        self._skipped_total = 0
        # device-side int32 skip counters, one per guarded chunk, drained
        # when ready (is_ready) — no host sync on the hot path
        self._pending_skipped = []
        self._chunk_cache = {}
        if self._health is not None:
            self._health.src = "loop"

    # ------------------------------------------------------------------
    def _build_chunk(self, kc, nb):
        import jax
        import jax.numpy as jnp
        from ..contrib.amp.loss_scaler import all_finite_flag
        opt = self._opt
        grad_of = self._make_grad_fn()
        guard = self._skip_nonfinite
        health_on = self._health is not None

        def body(carry, x):
            tr, aux, opt_state, step, skipped = carry
            rng = x[0]
            *xs, label = x[1:]
            step = step + 1
            loss, new_aux, grads = grad_of(tr, aux, rng, xs, label)
            new_tr, new_opt = opt.update(tr, grads, opt_state, step)
            new_aux = tuple(new_aux)
            if guard:
                # PR 3/4 guard semantics inside the scan: non-finite
                # grads leave params/opt/aux untouched; the step counter
                # still advances (documented fused-path behavior)
                flag = all_finite_flag(jax.tree.leaves(grads))
                if flag is not None:
                    ok = flag
                    keep = lambda new, old: jax.tree.map(
                        lambda a, b: jnp.where(ok, a, b), new, old)
                    new_tr = keep(new_tr, tr)
                    new_opt = keep(new_opt, opt_state)
                    new_aux = keep(new_aux, tuple(aux))
                    skipped = skipped + jnp.where(ok, 0, 1).astype(
                        jnp.int32)
            ys = loss
            if health_on:
                # per-inner-step stats ride the scan ys (stacked to
                # leading axis kc); computed AFTER the guard so a
                # skipped step reports update_ratio 0 while its raw
                # grads still carry the non-finite evidence
                ys = (loss, _health.train_step_health(
                    list(grads), list(tr), list(new_tr), loss=loss))
            return (new_tr, new_aux, new_opt, step, skipped), ys

        def pure_chunk(tr_vals, aux_vals, opt_state, step0, rngs, *flat):
            # stack the kc per-step batches step-major INSIDE the
            # program: inputs arrive individually placed (so the data
            # axis stays sharded) and the stack fuses into the scan
            xs = tuple(
                jnp.stack([flat[i * nb + j] for i in range(kc)])
                for j in range(nb))
            carry = (tr_vals, tuple(aux_vals), opt_state, step0,
                     jnp.zeros((), jnp.int32))
            (new_tr, new_aux, new_opt, _, skipped), ys = jax.lax.scan(
                body, carry, (rngs,) + xs)
            if health_on:
                losses, hstats = ys
                return (losses, new_tr, new_aux, new_opt, skipped,
                        hstats)
            return ys, new_tr, new_aux, new_opt, skipped

        donate = (0, 1, 2) if self._donate else ()
        outsh = (None, self._tr_shardings, self._aux_shardings,
                 self._state_out_shardings(), None)
        if health_on:
            outsh += (None,)
        return _telemetry.instrument_jit("loop", jax.jit(
            pure_chunk, out_shardings=outsh, donate_argnums=donate))

    # ------------------------------------------------------------------
    # mxtpu-lint: hot-path
    def step_chunk(self, batches):
        """Run ``len(batches)`` consecutive train steps as ONE compiled
        dispatch.  ``batches`` is a sequence of per-step batch tuples
        (the same ``*batch`` arguments :meth:`step` takes, uniform
        shapes).  Returns the [k]-shaped per-step loss array
        (non-blocking — async dispatch)."""
        from .. import random as _random
        import jax.numpy as jnp
        kc = len(batches)
        if kc == 0:
            raise MXNetError("step_chunk needs at least one batch")
        nb = len(batches[0])
        observe = bool(_telemetry.TRAINER.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        with _telemetry.trace_span("loop.chunk", cat="trainer"):
            with _telemetry.trace_span("loop.place", cat="transfer"):
                flat = tuple(self._shard_batch(b)
                             for bt in batches for b in bt)
            # one host key per inner step — the SAME stream k separate
            # step() calls would consume (chunking invariance + resume)
            rngs = jnp.stack([_random.new_key() for _ in range(kc)])
            key = (kc, nb) + self._build_key(flat)
            if key not in self._chunk_cache:
                self._chunk_cache[key] = self._build_chunk(kc, nb)
            step0 = jnp.asarray(self._step_count, jnp.int32)
            if self._health is not None:
                losses, self._tr_vals, self._aux_vals, self._opt_state, \
                    skipped, hstats = self._chunk_cache[key](
                        self._tr_vals, self._aux_vals, self._opt_state,
                        step0, rngs, *flat)
                self._health.submit(self._step_count, kc, hstats)
            else:
                losses, self._tr_vals, self._aux_vals, self._opt_state, \
                    skipped = self._chunk_cache[key](
                        self._tr_vals, self._aux_vals, self._opt_state,
                        step0, rngs, *flat)
        self._step_count += kc
        # k steps rode ONE compiled dispatch — the chunked-loop economy
        # the dispatch ledger should corroborate (mxtpu_dispatches_total
        # site "loop" grows by 1 while the step counter grows by kc)
        _telemetry.gauge("mxtpu_optimizer_dispatches_per_step").set(
            1.0 / kc)
        if self._skip_nonfinite:
            self._pending_skipped.append(skipped)
            self._drain_skipped(block=False)
        if observe:
            dt = _time.perf_counter() - t0
            _telemetry.TRAINER.publish(phase="step", seconds=dt,
                                       steps=kc)
            _telemetry.TRAINER.publish(phase="chunk", seconds=dt,
                                       steps=kc)
        return losses

    def run(self, data, steps=None, prefetch=True, buffers=None):
        """Drive chunked training over an iterable of batch tuples.

        ``data`` is any iterable yielding per-step batch tuples (a
        DataLoader, a generator, a list, or an already-built
        :class:`DevicePrefetcher`).  With ``prefetch=True`` (default) the
        iterable is wrapped in a DevicePrefetcher so fetch + h2d of the
        next batches overlap the current chunk's compute.  ``steps``
        caps the number of train steps (None = until exhausted); a
        short tail runs as a smaller chunk.  Returns the numpy array of
        per-step losses (the ONLY host sync, at the very end)."""
        import numpy as _np
        from ..io.prefetch import DevicePrefetcher
        owned = None
        if prefetch and not isinstance(data, DevicePrefetcher):
            owned = DevicePrefetcher(data, placement=self._shard_batch,
                                     buffers=buffers)
            source = iter(owned)
        else:
            source = iter(data)
        losses = []
        done = 0
        try:
            while steps is None or done < steps:
                want = self.loop_steps if steps is None \
                    else min(self.loop_steps, steps - done)
                chunk = []
                with _telemetry.trace_span("loop.next_batch",
                                           cat="dataloader"):
                    for _ in range(want):
                        try:
                            chunk.append(next(source))
                        except StopIteration:
                            break
                if not chunk:
                    break
                losses.append(self.step_chunk(chunk))
                done += len(chunk)
        finally:
            if owned is not None:
                owned.close()
        if self._skip_nonfinite:
            self.sync_nonfinite_guard()
        if self._health is not None:
            self._health.sync()
        if not losses:
            return _np.zeros((0,), _np.float32)
        return _np.concatenate([_np.asarray(x) for x in losses])

    # ------------------------------------------------------------------
    # non-finite guard surfacing (chunk-boundary reductions, PR 3/4)
    def _drain_skipped(self, block=False):
        rest = []
        for flag in self._pending_skipped:
            if block or flag.is_ready():
                n = int(flag)
                if n:
                    self._skipped_total += n
                    for _ in range(n):
                        _telemetry.FAULT.publish(site="loop.step",
                                                 event="skipped_step")
            else:
                rest.append(flag)
        self._pending_skipped = rest

    def sync_nonfinite_guard(self):
        """Block until every pending per-chunk skip counter is drained;
        returns the total skipped steps so far."""
        self._drain_skipped(block=True)
        return self._skipped_total

    @property
    def skipped_steps(self):
        """Skipped (non-finite) steps drained so far — exact after
        :meth:`sync_nonfinite_guard`."""
        return self._skipped_total

    # ------------------------------------------------------------------
    # checkpoint integration (AsyncCheckpointer trainer= protocol)
    def get_states(self):
        """Serialize loop progress + optimizer state for
        ``AsyncCheckpointer.save(..., trainer=loop)``."""
        import jax
        self._drain_skipped(block=True)
        if self._zero1:
            # save the PORTABLE (per-leaf, unpadded) layout, not the
            # flat padded shards: the blob is then independent of the
            # shard count (save at N=8, resume at N=4) and structurally
            # identical to a non-zero1 loop's state — checkpoints
            # interop in both directions
            tree = self._opt.portable_state(self._opt_state,
                                            fetch=_fetch_full)
        else:
            tree = jax.tree.map(_fetch_full, self._opt_state)
        return pickle.dumps({"loop": 1,
                             "step": self._step_count,
                             "skipped": self._skipped_total,
                             "opt_state": tree})

    def set_states(self, data):
        """Restore loop progress + optimizer state (counterpart of
        :meth:`get_states`; ``restore_into(..., trainer=loop)`` calls
        this).  Pair with :meth:`reload_params` after the checkpoint
        wrote the restored arrays into the net."""
        import jax
        st = pickle.loads(data)
        if not isinstance(st, dict) or st.get("loop") != 1:
            raise MXNetError(
                "checkpoint trainer states are not a CompiledLoop blob "
                "(saved from a different trainer type?)")
        self._step_count = int(st["step"])
        self._skipped_total = int(st.get("skipped", 0))
        self._pending_skipped = []
        if self._zero1:
            # blobs carry the portable per-leaf layout (see get_states);
            # re-flatten and re-pad for THIS mesh's shard count
            self._opt_state = self._opt.from_portable(st["opt_state"])
        else:
            self._opt_state = jax.tree.map(
                lambda old, new: _placed_copy(new, old.sharding),
                self._opt_state, st["opt_state"])
