"""Ring attention: sequence/context parallelism over the mesh (first-class
here; the reference has NONE — SURVEY §5.7 marks this as a capability the
TPU build adds beyond parity.  Public technique: Liu et al., "Ring
Attention with Blockwise Transformers", and the jax shard_map collective
idioms from the scaling book).

Each device holds a sequence shard of Q/K/V.  K/V blocks rotate around the
ring via ``lax.ppermute`` (ICI neighbor exchange) while a flash-style
streaming softmax (running max + running sum) accumulates exact attention —
memory O(T_local), comm fully overlapped by XLA's async collectives.
"""
from __future__ import annotations

from functools import partial

from ..base import MXNetError

__all__ = ["ring_attention", "local_flash_attention",
           "ring_attention_nd"]


def local_flash_attention(q, k, v, scale=None, causal=False,
                          q_offset=0, k_offset=0, key_mask=None):
    """Single-device exact attention with numerically-stable softmax.

    q: (..., Tq, D), k/v: (..., Tk, D).  q_offset/k_offset are the global
    positions of the first query/key element — used by the ring schedule's
    causal masking.  ``key_mask``: optional (B, Tk) validity indicator
    (>0 = valid) broadcast over heads/queries.
    """
    import jax.numpy as jnp
    if scale is None:
        scale = q.shape[-1] ** -0.5
    s = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        tq, tk = q.shape[-2], k.shape[-2]
        qpos = q_offset + jnp.arange(tq)[:, None]
        kpos = k_offset + jnp.arange(tk)[None, :]
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, -jnp.inf)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.where(jnp.isneginf(m), 0.0, m)  # fully-masked rows
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("...qk,...kd->...qd", p, v)
    return o / jnp.maximum(l, 1e-30)


def _ring_body_flash(q, k, v, kv_mask=None, *, axis_name, scale, causal):
    """Blockwise ring attention (Liu et al.'s full recipe): each ring
    step's LOCAL block runs through the Pallas flash kernel — the
    (T_local, T_local) score tile never materializes either — and
    blocks merge EXACTLY via their logsumexp:
    ``o <- w*o + w_b*o_b`` with ``w = exp(lse - logaddexp(lse, lse_b))``.
    Gradients flow through the merge because flash_attention_lse's
    custom_vjp accepts the lse cotangent (it folds into the kernels'
    dd term).  Requires (B, H, T_local, D) inputs.

    Causal cross-shard structure is data-dependent inside the loop
    (src vs my): handled with lax.switch over {full block, diagonal
    (causal) block, empty block} — all three branches trace the same
    shapes, SPMD-uniform."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..kernels.flash_attention import flash_attention_lse

    if q.ndim != 4:
        raise MXNetError(
            "blockwise ring attention needs (B, H, T_local, D) inputs")
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o0 = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    lse0 = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32)

    def blk(k_cur, v_cur, mask_cur, src):
        def run(causal_blk):
            o, l = flash_attention_lse(q, k_cur, v_cur, scale=scale,
                                       causal=causal_blk, mask=mask_cur)
            return o.astype(jnp.float32), l

        if not causal:
            return run(False)

        def full_blk():
            return run(False)

        def diag_blk():
            return run(True)

        def empty_blk():          # src > my: entirely in the future
            return jnp.zeros_like(o0), jnp.full(lse0.shape, -jnp.inf,
                                                jnp.float32)

        idx = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
        return lax.switch(idx, [full_blk, diag_blk, empty_blk])

    def body(step, carry):
        o, lse, k_cur, v_cur, mask_cur = carry
        src = (my - step) % n
        o_b, lse_b = blk(k_cur, v_cur, mask_cur, src)
        lse_new = jnp.logaddexp(lse, lse_b)
        safe = jnp.where(jnp.isneginf(lse_new), 0.0, lse_new)
        w_o = jnp.where(jnp.isneginf(lse), 0.0, jnp.exp(lse - safe))
        w_b = jnp.where(jnp.isneginf(lse_b), 0.0, jnp.exp(lse_b - safe))
        o = o * w_o[..., None] + o_b * w_b[..., None]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        mask_next = (None if mask_cur is None
                     else lax.ppermute(mask_cur, axis_name, perm))
        return o, lse_new, k_next, v_next, mask_next

    o, _, *_ = lax.fori_loop(0, n, body, (o0, lse0, k, v, kv_mask),
                             unroll=True)
    return o.astype(q.dtype)


def _ring_body(q, k, v, kv_mask=None, *, axis_name, scale, causal,
               use_flash=False):
    if use_flash:
        return _ring_body_flash(q, k, v, kv_mask, axis_name=axis_name,
                                scale=scale, causal=causal)
    return _ring_body_einsum(q, k, v, kv_mask, axis_name=axis_name,
                             scale=scale, causal=causal)


def _ring_body_einsum(q, k, v, kv_mask=None, *, axis_name, scale, causal):
    """Per-shard ring schedule (runs inside shard_map).

    ``kv_mask``: optional (B, T_local) key-validity indicator (>0 = valid),
    sequence-sharded like K/V; it rotates around the ring with them so
    padded keys stay masked on every device.  q/k/v are (B, H, T_local, D)
    when a mask is given, else any (..., T_local, D)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)              # ring size
    my = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    perm = [(i, (i + 1) % n) for i in range(n)]

    o = jnp.zeros(q.shape[:-1] + (v.shape[-1],), q.dtype)
    m = jnp.full(q.shape[:-1], -jnp.inf, jnp.float32)
    l = jnp.zeros(q.shape[:-1], jnp.float32)

    def body(step, carry):
        o, m, l, k_cur, v_cur, mask_cur = carry
        src = (my - step) % n                # whose K/V block we hold now
        s = jnp.einsum("...qd,...kd->...qk", q, k_cur).astype(jnp.float32) \
            * scale
        if causal:
            qpos = my * t_local + jnp.arange(t_local)[:, None]
            kpos = src * t_local + jnp.arange(t_local)[None, :]
            s = jnp.where(qpos >= kpos, s, -jnp.inf)
        if mask_cur is not None:
            s = jnp.where(mask_cur[:, None, None, :] > 0, s, -jnp.inf)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        corr = jnp.exp(jnp.where(jnp.isneginf(m), m_safe, m) - m_safe)
        l_new = l * corr + jnp.sum(p, axis=-1)
        o_new = o * corr[..., None].astype(o.dtype) + \
            jnp.einsum("...qk,...kd->...qd", p.astype(v_cur.dtype), v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        mask_next = (None if mask_cur is None
                     else lax.ppermute(mask_cur, axis_name, perm))
        return o_new, m_new, l_new, k_next, v_next, mask_next

    o, m, l, *_ = lax.fori_loop(0, n, body, (o, m, l, k, v, kv_mask))
    return (o / jnp.maximum(l, 1e-30)[..., None].astype(o.dtype))


def ring_attention(q, k, v, mesh=None, axis_name="seq", scale=None,
                   causal=False, use_flash=False):
    """Exact attention with Q/K/V sequence-sharded over ``axis_name``.

    q/k/v: (batch, heads, T, D) with T sharded over the mesh axis.
    Returns attention output with the same sharding.  Accepts jax arrays or
    NDArrays; batch/head dims may additionally be sharded over other axes.
    ``use_flash=True`` runs each ring step's local block through the
    Pallas flash kernel (blockwise ring attention — O(T_local) memory
    within the block as well); results are numerically the same path.
    """
    import jax
    from jax.sharding import PartitionSpec as P
    from ._shmap import shard_map
    from . import mesh as mesh_mod
    from ..ndarray.ndarray import NDArray

    mesh = mesh or mesh_mod.current_mesh()
    if mesh is None:
        raise MXNetError("ring_attention needs a mesh")
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    wrap = isinstance(q, NDArray)
    if wrap:
        q, k, v = q._data, k._data, v._data
    if scale is None:
        scale = q.shape[-1] ** -0.5

    spec = P(None, None, axis_name, None)
    fn = shard_map(
        partial(_ring_body, axis_name=axis_name, scale=scale,
                causal=causal, use_flash=use_flash),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    out = fn(q, k, v)
    if wrap:
        return NDArray(out)
    return out


def ring_attention_nd(q, k, v, mesh=None, axis_name="seq", scale=None,
                      causal=False):
    """NDArray-facing alias (mx.nd layer integration)."""
    return ring_attention(q, k, v, mesh=mesh, axis_name=axis_name,
                          scale=scale, causal=causal)
