"""Ulysses sequence parallelism: all-to-all context parallelism over the
mesh (first-class here; the reference has NONE — SURVEY §5.7.  Public
technique: DeepSpeed-Ulysses, Jacobs et al. 2023; jax shard_map
collective idioms from the scaling book).

Q/K/V arrive sequence-sharded (each device holds T/N positions of every
head).  One ``lax.all_to_all`` re-partitions to head-sharded (each
device holds ALL positions of H/N heads), local attention runs exactly
and unblocked on the MXU, and a final all-to-all restores sequence
sharding.  Four all-to-alls per attention (Q/K/V in, output out; plus an
all_gather for the optional key mask) — a constant collective count vs
the ring's N ppermute rounds, favorable when H >= N — at the cost of
requiring H % N == 0.
"""
from __future__ import annotations

from functools import partial

from ..base import MXNetError

__all__ = ["ulysses_attention"]


def _ulysses_body(q, k, v, mask=None, *, axis_name, scale, causal,
                  use_flash=False):
    """Per-shard body (runs inside shard_map).

    q/k/v: (B, H, T_local, D) sequence shards; optional ``mask``
    (B, T_local) key-validity shard.  Returns the (B, H, T_local, D)
    attention output shard.  ``use_flash`` runs the post-all-to-all
    full-sequence attention through the Pallas flash kernel (the
    (B, H/n, T, D) gathered shape is exactly the kernel's contract; the
    dispatcher still falls back to XLA for non-tile-aligned T)."""
    from jax import lax
    from .ring import local_flash_attention

    # seq-sharded -> head-sharded: split heads into n groups, gather the
    # full sequence for our group
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)                  # (B, H/n, T, D)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                        tiled=True)
    full_mask = (None if mask is None else
                 lax.all_gather(mask, axis_name, axis=1,
                                tiled=True))         # (B, T)
    if use_flash:
        from ..kernels import flash_attention
        oh = flash_attention(qh, kh, vh, scale=scale, causal=causal,
                             mask=full_mask)
    else:
        oh = local_flash_attention(qh, kh, vh, scale=scale,
                                   causal=causal, key_mask=full_mask)
    # head-sharded -> seq-sharded
    return lax.all_to_all(oh, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def ulysses_attention(q, k, v, mesh=None, axis_name="seq", scale=None,
                      causal=False, mask=None, use_flash=False):
    """Exact attention with Q/K/V sequence-sharded over ``axis_name``,
    computed with the DeepSpeed-Ulysses all-to-all schedule.

    q/k/v: (batch, heads, T, D), T sharded over the mesh axis; heads
    must be divisible by the axis size.  ``mask``: optional (batch, T)
    key-validity array, sequence-sharded like K/V.  Accepts jax arrays
    or NDArrays; returns the same sharding as the inputs."""
    from jax.sharding import PartitionSpec as P
    from ._shmap import shard_map
    from . import mesh as mesh_mod
    from ..ndarray.ndarray import NDArray

    mesh = mesh or mesh_mod.current_mesh()
    if mesh is None:
        raise MXNetError("ulysses_attention needs a mesh")
    if axis_name not in mesh.axis_names:
        raise MXNetError(f"mesh has no axis {axis_name!r}")
    n = mesh.shape[axis_name]
    wrap = isinstance(q, NDArray)
    if wrap:
        q, k, v = q._data, k._data, v._data
        if mask is not None and isinstance(mask, NDArray):
            mask = mask._data
    if q.shape[1] % n:
        raise MXNetError(
            f"ulysses_attention: heads ({q.shape[1]}) must be divisible "
            f"by the '{axis_name}' axis size ({n}); use ring_attention "
            "for head counts smaller than the sequence axis")
    if scale is None:
        scale = q.shape[-1] ** -0.5

    spec = P(None, None, axis_name, None)
    if mask is not None:
        fn = shard_map(
            partial(_ulysses_body, axis_name=axis_name, scale=scale,
                    causal=causal, use_flash=use_flash),
            mesh=mesh, in_specs=(spec, spec, spec, P(None, axis_name)),
            out_specs=spec, check_vma=False)
        out = fn(q, k, v, mask)
    else:
        fn = shard_map(
            partial(_ulysses_body, axis_name=axis_name, scale=scale,
                    causal=causal, use_flash=use_flash),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        out = fn(q, k, v)
    return NDArray(out) if wrap else out
