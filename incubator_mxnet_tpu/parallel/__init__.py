"""``mx.parallel``: TPU-native distribution (SURVEY §2.4, §5.8).

The reference's distribution surface (DataParallelExecutorGroup, KVStore
local/device/nccl/dist_sync, ps-lite, Horovod hooks) re-designed as mesh +
shardings + one compiled step:

  make_mesh / mesh_scope      device mesh with named axes
  SPMDTrainer                 whole train step (fwd+bwd+psum+opt) in one jit
  shard_params                regex→PartitionSpec tensor parallelism
  fsdp_rules                  ZeRO-3-class full parameter sharding over data
  zero1 / Zero1Optimizer      ZeRO-1 weight-update sharding: flat dp-sharded
                              optimizer state + in-program weight all-gather
  ring_attention              sequence parallelism over the mesh (beyond
                              reference parity)
  ulysses_attention           all-to-all sequence parallelism (DeepSpeed-
                              Ulysses schedule; beyond reference parity)
  distributed.initialize      multi-host bootstrap (DMLC_* env compat)
"""
from .mesh import (make_mesh, local_mesh, current_mesh, mesh_scope,
                   replicated, shard_spec, named_sharding,
                   device_put_sharded)
from .spmd import (SPMDTrainer, shard_params, data_sharding,
                   exact_rule, fsdp_rules)
from .loop import CompiledLoop
from .ring import ring_attention, local_flash_attention
from .ulysses import ulysses_attention
from .pipeline import (gpipe, stack_stage_params, pipe_specs,
                       stack_block_stages, PipelineTrainer)
from .zero1 import (Zero1Optimizer, ShardSpec, build_shard_spec,
                    per_replica_state_bytes)
from . import optim
from . import zero1
from . import distributed

__all__ = ["make_mesh", "local_mesh", "current_mesh", "mesh_scope",
           "replicated", "shard_spec", "named_sharding",
           "device_put_sharded", "SPMDTrainer", "CompiledLoop",
           "shard_params", "fsdp_rules",
           "data_sharding", "exact_rule", "ring_attention",
           "local_flash_attention", "ulysses_attention", "gpipe",
           "stack_stage_params", "pipe_specs", "stack_block_stages",
           "PipelineTrainer", "Zero1Optimizer", "ShardSpec",
           "build_shard_spec", "per_replica_state_bytes", "optim",
           "zero1", "distributed"]
