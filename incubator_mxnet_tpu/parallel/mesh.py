"""Device-mesh management (SURVEY §2.4: the TPU-native replacement for the
reference's multi-device Context lists + KVStore comm topology —
src/kvstore/comm.h, comm_tree.h).

A Mesh names axes ('data', 'model', 'seq', 'pipe', 'expert'...) over the
device grid; shardings are NamedSharding(PartitionSpec) over those axes and
XLA compiles the collectives (the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert collectives).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as _np

from ..base import MXNetError

__all__ = ["make_mesh", "current_mesh", "mesh_scope", "replicated",
           "shard_spec", "named_sharding", "device_put_sharded",
           "local_mesh"]

_tls = threading.local()


def make_mesh(axes: Dict[str, int], devices=None):
    """Create a ``jax.sharding.Mesh`` with named axes.

    axes: ordered dict-like {axis_name: size}; -1 for one axis means "all
    remaining devices".  devices defaults to ``jax.devices()``.
    """
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = list(axes.values())
    n_unknown = sum(1 for s in sizes if s == -1)
    if n_unknown > 1:
        raise MXNetError("at most one mesh axis may be -1")
    known = int(_np.prod([s for s in sizes if s != -1])) if sizes else 1
    if n_unknown:
        if len(devices) % known:
            raise MXNetError(
                f"{len(devices)} devices not divisible by {known}")
        sizes = [len(devices) // known if s == -1 else s for s in sizes]
    total = int(_np.prod(sizes)) if sizes else 1
    if total > len(devices):
        raise MXNetError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, have "
            f"{len(devices)}")
    grid = _np.array(devices[:total]).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def local_mesh(axes: Dict[str, int]):
    """Mesh over this process's local devices only."""
    import jax
    return make_mesh(axes, jax.local_devices())


def current_mesh():
    return getattr(_tls, "mesh", None)


class mesh_scope:
    """``with mesh_scope(mesh):`` sets the ambient mesh used by the
    parallel helpers (and KVStore('tpu'))."""

    def __init__(self, mesh):
        self._mesh = mesh

    def __enter__(self):
        self._prev = getattr(_tls, "mesh", None)
        _tls.mesh = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        _tls.mesh = self._prev
        return False


def shard_spec(*axes):
    """PartitionSpec shorthand: shard_spec('data', None) etc."""
    from jax.sharding import PartitionSpec
    return PartitionSpec(*axes)


def replicated(mesh=None):
    from jax.sharding import NamedSharding, PartitionSpec
    mesh = mesh or current_mesh()
    return NamedSharding(mesh, PartitionSpec())


def named_sharding(mesh, *axes):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*axes))


def device_put_sharded(array, mesh, *axes):
    """Place (a jax array or numpy) with the given PartitionSpec axes."""
    import jax
    return jax.device_put(array, named_sharding(mesh, *axes))
