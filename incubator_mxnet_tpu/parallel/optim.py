"""Functional optimizers for the compiled SPMD train step.

The imperative ``mx.optimizer`` classes (reference parity layer) mutate
NDArrays eagerly; inside one jitted+sharded train step the update must be a
pure function of (params, grads, state, step).  The update-rule arithmetic
lives in ``optimizer/cores.py`` — ONE set of pure per-leaf cores shared
with the eager ops (ndarray/optimizer_ops.py) and the fused whole-tree
Trainer step (optimizer/fused.py); this module lifts those cores to
pytrees — the analog of the reference's "server-side optimizer"
(update_on_kvstore), except the "server" is the compiled program itself
(SURVEY §2.4).
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

from ..base import MXNetError

__all__ = ["FunctionalOptimizer", "sgd", "adam", "adamw", "rmsprop",
           "adagrad", "nag", "lamb", "create"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def _cores():
    from ..optimizer import cores
    return cores


class FunctionalOptimizer(NamedTuple):
    """(init_fn, update_fn) pair.

    init(params) -> state;
    update(params, grads, state, step) -> (new_params, new_state)
    where step is a traced int32 scalar (1-based).

    ``elementwise`` marks rules whose update is a pure per-element map
    (given prepped grads) — the precondition for ZeRO-1 flat sharding
    (parallel/zero1.py): a contiguous slice of the flattened tree can be
    updated alone.  LAMB's per-tensor trust ratio is the exception.
    """
    init: Any
    update: Any
    elementwise: bool = True


def _prep(c, g, w, rescale_grad, clip_gradient, wd):
    """Shared grad prologue: rescale → clip → fold wd (each stage decided
    statically, mirroring the fused eager path)."""
    return c.prep_grad(
        g,
        rescale_grad if float(rescale_grad) != 1.0 else None,
        clip_gradient if clip_gradient else None,
        wd if wd else None, w)


def _zeros_state(params):
    import jax
    return jax.tree.map(lambda p: _jnp().zeros_like(p), params)


def sgd(learning_rate=0.01, momentum=0.0, wd=0.0, lr_schedule=None,
        rescale_grad=1.0, clip_gradient=None):
    import jax
    c = _cores()

    def init(params):
        if momentum == 0.0:
            return {}
        return {"mom": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate

        def prep(g, w):
            return _prep(c, g, w, rescale_grad, clip_gradient, wd)
        if momentum == 0.0:
            new_p = jax.tree.map(
                lambda w, g: c.sgd(w, prep(g, w), lr), params, grads)
            return new_p, state
        pairs = jax.tree.map(
            lambda w, g, m: c.sgd_momentum(w, prep(g, w), m, lr, momentum),
            params, grads, state["mom"])
        new_p = jax.tree.map(lambda w, pr: pr[0], params, pairs)
        new_mom = jax.tree.map(lambda w, pr: pr[1], params, pairs)
        return new_p, {"mom": new_mom}
    return FunctionalOptimizer(init, update)


def nag(learning_rate=0.01, momentum=0.9, wd=0.0, lr_schedule=None,
        rescale_grad=1.0, clip_gradient=None):
    """Nesterov momentum SGD (reference: nag_mom_update)."""
    import jax
    c = _cores()

    def init(params):
        return {"mom": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        pairs = jax.tree.map(
            lambda w, g, m: c.nag_momentum(
                w, _prep(c, g, w, rescale_grad, clip_gradient, wd),
                m, lr, momentum),
            params, grads, state["mom"])
        new_p = jax.tree.map(lambda w, pr: pr[0], params, pairs)
        new_mom = jax.tree.map(lambda w, pr: pr[1], params, pairs)
        return new_p, {"mom": new_mom}
    return FunctionalOptimizer(init, update)


def adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
         lr_schedule=None, rescale_grad=1.0, clip_gradient=None):
    import jax
    jnp = _jnp()
    c = _cores()

    def init(params):
        return {"m": _zeros_state(params), "v": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        t = step.astype(jnp.float32)
        coef = jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)
        # wd folds into the gradient BEFORE the moment updates, matching
        # the eager adam_update (reference AdamUpdate) — not AdamW-style;
        # bias correction folds into lr exactly like the eager Adam class
        triples = jax.tree.map(
            lambda w, g, m, v: c.adam(
                w, _prep(c, g, w, rescale_grad, clip_gradient, wd),
                m, v, lr * coef, beta1, beta2, epsilon),
            params, grads, state["m"], state["v"])
        new_p = jax.tree.map(lambda w, tr: tr[0], params, triples)
        new_m = jax.tree.map(lambda w, tr: tr[1], params, triples)
        new_v = jax.tree.map(lambda w, tr: tr[2], params, triples)
        return new_p, {"m": new_m, "v": new_v}
    return FunctionalOptimizer(init, update)


def adamw(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
          wd=0.0, lr_schedule=None, rescale_grad=1.0, clip_gradient=None):
    """AdamW — decoupled weight decay (reference: contrib.adamw)."""
    import jax
    jnp = _jnp()
    c = _cores()

    def init(params):
        return {"m": _zeros_state(params), "v": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        t = step.astype(jnp.float32)
        coef1 = 1.0 - beta1 ** t
        coef2 = 1.0 - beta2 ** t
        triples = jax.tree.map(
            lambda w, g, m, v: c.adamw(
                w, _prep(c, g, None, rescale_grad, clip_gradient, 0.0),
                m, v, lr, wd, beta1, beta2, epsilon, coef1, coef2),
            params, grads, state["m"], state["v"])
        new_p = jax.tree.map(lambda w, tr: tr[0], params, triples)
        new_m = jax.tree.map(lambda w, tr: tr[1], params, triples)
        new_v = jax.tree.map(lambda w, tr: tr[2], params, triples)
        return new_p, {"m": new_m, "v": new_v}
    return FunctionalOptimizer(init, update)


def rmsprop(learning_rate=0.001, gamma1=0.9, epsilon=1e-8, wd=0.0,
            lr_schedule=None, rescale_grad=1.0, clip_gradient=None):
    """Non-centered RMSProp (reference: rmsprop_update)."""
    import jax
    c = _cores()

    def init(params):
        return {"n": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        pairs = jax.tree.map(
            lambda w, g, n: c.rmsprop(
                w, _prep(c, g, w, rescale_grad, clip_gradient, wd),
                n, lr, gamma1, epsilon),
            params, grads, state["n"])
        new_p = jax.tree.map(lambda w, pr: pr[0], params, pairs)
        new_n = jax.tree.map(lambda w, pr: pr[1], params, pairs)
        return new_p, {"n": new_n}
    return FunctionalOptimizer(init, update)


def adagrad(learning_rate=0.01, epsilon=1e-7, wd=0.0, lr_schedule=None,
            rescale_grad=1.0, clip_gradient=None):
    """AdaGrad (reference: adagrad_update — decoupled wd, epsilon inside
    the sqrt)."""
    import jax
    c = _cores()

    def init(params):
        return {"h": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        pairs = jax.tree.map(
            lambda w, g, h: c.adagrad(
                w, _prep(c, g, None, rescale_grad, clip_gradient, 0.0),
                h, lr, epsilon, wd),
            params, grads, state["h"])
        new_p = jax.tree.map(lambda w, pr: pr[0], params, pairs)
        new_h = jax.tree.map(lambda w, pr: pr[1], params, pairs)
        return new_p, {"h": new_h}
    return FunctionalOptimizer(init, update)


def lamb(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6, wd=0.0,
         lr_schedule=None, rescale_grad=1.0, clip_gradient=None):
    """LAMB with per-tensor trust ratio (reference: LAMB optimizer +
    lamb_update_phase1/2).  The trust ratio is a per-tensor reduction, so
    this rule is NOT elementwise — ZeRO-1 flat sharding excludes it."""
    import jax
    jnp = _jnp()
    c = _cores()

    def init(params):
        return {"m": _zeros_state(params), "v": _zeros_state(params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        t = step.astype(jnp.float32)
        pairs = jax.tree.map(
            lambda m, g, v: c.moments(
                m, v, _prep(c, g, None, rescale_grad, clip_gradient, 0.0),
                beta1, beta2),
            state["m"], grads, state["v"])
        new_m = jax.tree.map(lambda m, pr: pr[0], state["m"], pairs)
        new_v = jax.tree.map(lambda m, pr: pr[1], state["m"], pairs)

        def upd(w, m, v):
            mhat = m / (1 - beta1 ** t)
            vhat = v / (1 - beta2 ** t)
            u = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w
            r1 = jnp.linalg.norm(w.ravel())
            r2 = jnp.linalg.norm(u.ravel())
            ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
            return w - lr * ratio * u
        new_p = jax.tree.map(upd, params, new_m, new_v)
        return new_p, {"m": new_m, "v": new_v}
    return FunctionalOptimizer(init, update, elementwise=False)


_REGISTRY = {"sgd": sgd, "nag": nag, "adam": adam, "adamw": adamw,
             "rmsprop": rmsprop, "adagrad": adagrad, "lamb": lamb}


def create(name, **kwargs) -> FunctionalOptimizer:
    if isinstance(name, FunctionalOptimizer):
        return name
    if name not in _REGISTRY:
        raise MXNetError(
            f"unknown functional optimizer {name!r} "
            f"(have {sorted(_REGISTRY)}); momentum= maps onto sgd")
    if name == "sgd" and "momentum" not in kwargs:
        kwargs.setdefault("momentum", 0.0)
    return _REGISTRY[name](**kwargs)
