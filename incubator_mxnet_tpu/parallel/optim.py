"""Functional optimizer cores for the compiled SPMD train step.

The imperative ``mx.optimizer`` classes (reference parity layer) mutate
NDArrays eagerly; inside one jitted+sharded train step the update must be a
pure function of (params, grads, state, step).  These mirror the same
update rules as ndarray/optimizer_ops.py (reference:
src/operator/optimizer_op.cc) in pytree form — the analog of the
reference's "server-side optimizer" (update_on_kvstore), except the
"server" is the compiled program itself (SURVEY §2.4).
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple

from ..base import MXNetError

__all__ = ["FunctionalOptimizer", "sgd", "adam", "lamb", "create"]


def _jnp():
    import jax.numpy as jnp
    return jnp


class FunctionalOptimizer(NamedTuple):
    """(init_fn, update_fn) pair.

    init(params) -> state;
    update(params, grads, state, step) -> (new_params, new_state)
    where step is a traced int32 scalar (1-based).
    """
    init: Any
    update: Any


def sgd(learning_rate=0.01, momentum=0.0, wd=0.0, lr_schedule=None):
    import jax

    def init(params):
        if momentum == 0.0:
            return {}
        return {"mom": jax.tree.map(lambda p: _jnp().zeros_like(p), params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        if momentum == 0.0:
            new_p = jax.tree.map(lambda w, g: w - lr * (g + wd * w),
                                 params, grads)
            return new_p, state
        new_mom = jax.tree.map(
            lambda m, g, w: momentum * m - lr * (g + wd * w),
            state["mom"], grads, params)
        new_p = jax.tree.map(lambda w, m: w + m, params, new_mom)
        return new_p, {"mom": new_mom}
    return FunctionalOptimizer(init, update)


def adam(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, wd=0.0,
         lr_schedule=None):
    import jax
    jnp = _jnp()

    def init(params):
        z = lambda p: jnp.zeros_like(p)  # noqa: E731
        return {"m": jax.tree.map(z, params),
                "v": jax.tree.map(z, params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        t = step.astype(jnp.float32)
        coef = jnp.sqrt(1.0 - beta2 ** t) / (1.0 - beta1 ** t)
        # wd folds into the gradient BEFORE the moment updates, matching the
        # eager adam_update (ndarray/optimizer_ops.py / reference
        # src/operator/optimizer_op-inl.h AdamUpdate) — not AdamW-style
        geff = jax.tree.map(lambda g, w: g + wd * w, grads, params)
        new_m = jax.tree.map(lambda m, g: beta1 * m + (1 - beta1) * g,
                             state["m"], geff)
        new_v = jax.tree.map(lambda v, g: beta2 * v + (1 - beta2) * g * g,
                             state["v"], geff)
        new_p = jax.tree.map(
            lambda w, m, v: w - lr * coef * m / (jnp.sqrt(v) + epsilon),
            params, new_m, new_v)
        return new_p, {"m": new_m, "v": new_v}
    return FunctionalOptimizer(init, update)


def lamb(learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-6, wd=0.0,
         lr_schedule=None):
    """LAMB with per-tensor trust ratio (reference: LAMB optimizer +
    lamb_update_phase1/2)."""
    import jax
    jnp = _jnp()

    def init(params):
        z = lambda p: jnp.zeros_like(p)  # noqa: E731
        return {"m": jax.tree.map(z, params),
                "v": jax.tree.map(z, params)}

    def update(params, grads, state, step):
        lr = lr_schedule(step) if lr_schedule is not None else learning_rate
        t = step.astype(jnp.float32)
        new_m = jax.tree.map(lambda m, g: beta1 * m + (1 - beta1) * g,
                             state["m"], grads)
        new_v = jax.tree.map(lambda v, g: beta2 * v + (1 - beta2) * g * g,
                             state["v"], grads)

        def upd(w, m, v):
            mhat = m / (1 - beta1 ** t)
            vhat = v / (1 - beta2 ** t)
            u = mhat / (jnp.sqrt(vhat) + epsilon) + wd * w
            r1 = jnp.linalg.norm(w.ravel())
            r2 = jnp.linalg.norm(u.ravel())
            ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
            return w - lr * ratio * u
        new_p = jax.tree.map(upd, params, new_m, new_v)
        return new_p, {"m": new_m, "v": new_v}
    return FunctionalOptimizer(init, update)


_REGISTRY = {"sgd": sgd, "adam": adam, "lamb": lamb}


def create(name, **kwargs) -> FunctionalOptimizer:
    if isinstance(name, FunctionalOptimizer):
        return name
    if name not in _REGISTRY:
        raise MXNetError(
            f"unknown functional optimizer {name!r} "
            f"(have {sorted(_REGISTRY)}); momentum= maps onto sgd")
    if name == "sgd" and "momentum" not in kwargs:
        kwargs.setdefault("momentum", 0.0)
    return _REGISTRY[name](**kwargs)
