"""SPMD training: ONE compiled train step over a device mesh.

This is the TPU-native replacement for the reference's entire distributed
stack (SURVEY §2.4, §5.8): DataParallelExecutorGroup batch slicing +
KVStore push/pull + ps-lite servers (reference:
python/mxnet/module/executor_group.py, src/kvstore/kvstore_dist.h) collapse
into a single ``jax.jit`` over a Mesh:

* batch sharded over the 'data' axis  → gradient allreduce is compiled in
  (GSPMD inserts psum over ICI/DCN; no server round-trips);
* parameters sharded by regex rules   → tensor parallelism, strictly more
  than the reference's manual group2ctx placement;
* the optimizer runs inside the step  → the reference's "server-side
  optimizer" (update_on_kvstore) with the compiled program as the server;
* aux state (BatchNorm stats) flows functionally through the step.

Multi-host: same code — initialize jax.distributed (parallel.distributed),
build the mesh over all processes' devices, feed each process its local
batch shard.
"""
from __future__ import annotations

import re
import time as _time
import weakref as _weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .. import health as _health
from .. import telemetry as _telemetry
from .. import telemetry_device as _telemetry_device
from ..ndarray.ndarray import NDArray
from ..gluon.block import functional_call
from . import mesh as mesh_mod
from . import optim as fopt

__all__ = ["SPMDTrainer", "shard_params", "data_sharding",
           "exact_rule", "fsdp_rules"]


def _fetch_full(v):
    """Materialize a (possibly sharded) jax array as full numpy.
    Multi-host: shards on other processes are not addressable; allgather
    over DCN first (single-host path is a plain copy)."""
    if getattr(v, "is_fully_addressable", True):
        return _np.asarray(v)
    from jax.experimental import multihost_utils
    return _np.asarray(multihost_utils.process_allgather(v, tiled=True))


def _placed_copy(x, s):
    """Place ``x`` per sharding ``s`` as a FRESH buffer.  device_put may
    ALIAS the input (even via a distinct Array object) when placement
    already matches — a later donated step would then delete the source
    array; always copy so the source stays usable (the copy is reclaimed
    by donation on the first step)."""
    import jax
    import jax.numpy as jnp
    return jnp.copy(jax.device_put(x, s))


def exact_rule(param, spec):
    """One exact-name sharding rule ``("^<name>$", spec)`` for a
    Parameter (or anything with ``.name``) — the building block every
    ``*_rules(block=...)`` derivation uses; immune to custom prefixes,
    unlike the auto-prefix regex rule lists."""
    return (f"^{re.escape(param.name)}$", spec)


def data_sharding(mesh, data_axis="data"):
    """Batch-dim sharding for input arrays."""
    return mesh_mod.named_sharding(mesh, data_axis)


def shard_params(params: Dict[str, object], mesh, rules=None):
    """Apply (regex, PartitionSpec) rules to a name→array dict; first match
    wins, default replicated.  Returns name→NamedSharding.

    Warns on DEAD rules (patterns matching no parameter): a sharding rule
    that silently matches nothing replicates the weights it was meant to
    shard — the failure mode of auto-prefix regexes applied to a
    custom-``prefix=`` model (use the family's ``tp_rules(block=net)``).
    Patterns carrying a ``(?#optional)`` regex comment (a model-variant
    rule, e.g. an untied-head rule on a tied model) are exempt."""
    from jax.sharding import NamedSharding, PartitionSpec
    out = {}
    rules = list(rules or [])
    hit = [False] * len(rules)
    for name in params:
        spec = None
        for i, (pat, s) in enumerate(rules):
            if re.search(pat, name):
                # FIRST match decides the spec, but every matching rule
                # counts as live — a rule shadowed by an earlier one is
                # not dead (its weights are sharded, just by the earlier
                # rule)
                hit[i] = True
                if spec is None:
                    spec = s if isinstance(s, PartitionSpec) \
                        else PartitionSpec(*s)
        out[name] = NamedSharding(mesh, spec or PartitionSpec())
    # a "(?#optional)" regex comment inside the pattern marks the rule
    # as covering a model VARIANT (e.g. an untied-head rule on a tied
    # model) — exempt from the dead warning; any other dead rule means
    # the weights it targets silently replicate
    dead = [rules[i][0] for i in range(len(rules))
            if not hit[i] and "(?#optional)" not in rules[i][0]]
    if dead:
        import warnings
        warnings.warn(
            "sharding rules matched no parameter (their weights stay "
            f"REPLICATED): {dead}; with custom prefix= models derive "
            "exact-name rules via tp_rules(block=net)", stacklevel=2)
    return out


def fsdp_rules(block, axis="data", min_size=1 << 16, mesh=None):
    """Fully-sharded data parallelism (ZeRO-3 class) as sharding rules.

    Every parameter of at least ``min_size`` elements gets its largest
    (mesh-divisible, when ``mesh`` is given) axis sharded over the DATA
    axis, so each device stores 1/N of the big weights; GSPMD then
    compiles the FSDP communication schedule automatically — all-gather
    of each layer's weights before its compute, reduce-scatter of its
    gradients in the backward — while the batch stays sharded over the
    same axis.  Small parameters (biases, norms) remain replicated,
    standard FSDP practice: their all-gather latency would exceed the
    memory saved.

    Compose with ``shard_optimizer_state=True``: optimizer-state leaves
    inherit each param's sharding, so moments for FSDP-sharded weights
    are already distributed and ZeRO-1 covers the replicated remainder
    (see ``_make_state_shardings``).

    Reference analog: none — the reference's kvstore replicates all
    weights per device (SURVEY §2.4); beyond-parity with
    dp/tp/sp/ep/pp.  Pattern: GSPMD ("automatic sharding propagation")
    + the ZeRO paper's stage-3 partitioning, expressed as
    PartitionSpecs instead of a runtime."""
    from jax.sharding import PartitionSpec as P
    if mesh is not None and axis not in mesh.shape:
        raise MXNetError(
            f"fsdp_rules: mesh has no axis {axis!r} "
            f"(axes: {tuple(mesh.shape)})")
    rules = []
    n = mesh.shape[axis] if mesh is not None else None
    for p in block.collect_params().values():
        if p._data is None:
            raise MXNetError(
                "initialize the net and run one forward before deriving "
                "fsdp_rules (deferred shapes must be settled)")
        v = p.data()
        if v.size < min_size:
            continue
        shape = tuple(v.shape)
        pick = None
        for d in sorted(range(len(shape)), key=lambda i: -shape[i]):
            if n is None or (shape[d] > 0 and shape[d] % n == 0):
                pick = d
                break
        if pick is None:
            continue           # no divisible axis: stays replicated
        spec = [None] * len(shape)
        spec[pick] = axis
        rules.append(exact_rule(p, P(*spec)))
    return rules


class SPMDTrainer:
    """Compile a Block + loss + functional optimizer into one sharded step.

    Usage::

        mesh = parallel.make_mesh({"data": -1})
        trainer = SPMDTrainer(net, loss_fn, "adam",
                              {"learning_rate": 1e-3}, mesh=mesh)
        for x, y in loader:
            loss = trainer.step(x, y)   # one XLA program, psum inside
        trainer.sync_to_block()         # write params back to net
    """

    def __new__(cls, *args, **kwargs):
        # pipeline_axis= switches to the GPipe trainer (stacked-stage
        # parameter storage over a data x pipe mesh) — one entry point
        # for every parallel axis; see parallel/pipeline.py
        if cls is SPMDTrainer and kwargs.get("pipeline_axis"):
            from .pipeline import PipelineTrainer
            return object.__new__(PipelineTrainer)
        return object.__new__(cls)

    def __init__(self, net, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, data_axis="data", sharding_rules=None,
                 extra_input_shardings=None, donate=True,
                 shard_optimizer_state=False, zero1=None,
                 pipeline_axis=None,
                 pipeline_microbatches=None, pipeline_schedule=None,
                 accum_steps=None):
        import jax
        from ..base import getenv_bool
        if pipeline_axis is not None:
            # only reachable from a subclass that didn't override
            # __init__ — SPMDTrainer itself dispatches in __new__
            raise MXNetError(
                "pipeline_axis is handled by parallel.PipelineTrainer")
        if pipeline_microbatches is not None:
            raise MXNetError(
                "pipeline_microbatches without pipeline_axis — pass "
                "pipeline_axis=<mesh axis> to request pipelining")
        if pipeline_schedule is not None:
            raise MXNetError(
                "pipeline_schedule without pipeline_axis — pass "
                "pipeline_axis=<mesh axis> to request pipelining")
        self._net = net
        self._loss = loss_fn
        self._mesh = mesh or mesh_mod.current_mesh()
        if self._mesh is None:
            raise MXNetError("SPMDTrainer needs a mesh (parallel.make_mesh)")
        self._data_axis = data_axis
        self._donate = donate
        self._opt = fopt.create(optimizer, **(optimizer_params or {}))
        self._zero1 = getenv_bool("MXNET_ZERO1", False) if zero1 is None \
            else bool(zero1)
        if self._zero1 and shard_optimizer_state:
            raise MXNetError(
                "zero1 and shard_optimizer_state are two spellings of "
                "the same memory optimization (flat contiguous shards "
                "vs per-leaf axis sharding) — pick one")
        if self._zero1 and not getattr(self._opt, "elementwise", True):
            if zero1 is not None:
                raise MXNetError(
                    "zero1: this optimizer's update is not elementwise "
                    "(per-tensor reductions, e.g. LAMB's trust ratio, "
                    "straddle shard boundaries) — drop zero1= or pick "
                    "an elementwise rule")
            # env-driven request (MXNET_ZERO1=1): degrade gracefully,
            # mirroring the eager Trainer's fused-path fallback
            import warnings
            warnings.warn(
                "MXNET_ZERO1=1 ignored: optimizer update is not "
                "elementwise (per-tensor reductions straddle shard "
                "boundaries); training proceeds unsharded", stacklevel=2)
            self._zero1 = False

        params_all = list(net.collect_params().values())
        for p in params_all:
            if p._data is None:
                raise MXNetError(
                    "initialize the net and run one forward before "
                    "building an SPMDTrainer (deferred shapes must be "
                    "settled)")
        self._trainable = [p for p in params_all if p.grad_req != "null"]
        self._aux = [p for p in params_all if p.grad_req == "null"]

        shardings = shard_params(
            {p.name: p.data()._data for p in self._trainable + self._aux},
            self._mesh, sharding_rules)
        self._tr_shardings = tuple(shardings[p.name]
                                   for p in self._trainable)
        self._aux_shardings = tuple(shardings[p.name] for p in self._aux)

        # place parameter values on the mesh per their shardings (see
        # _placed_copy for why a fresh buffer is mandatory here)
        self._tr_vals = tuple(
            _placed_copy(p.data()._data, s)
            for p, s in zip(self._trainable, self._tr_shardings))
        self._aux_vals = tuple(
            _placed_copy(p.data()._data, s)
            for p, s in zip(self._aux, self._aux_shardings))
        # ZeRO-1 weight-update sharding (paper: "Automatic Cross-Replica
        # Sharding of Weight Update in Data-Parallel Training",
        # arXiv:2004.13336) — two tiers of the same idea:
        #   zero1=True: parallel/zero1.Zero1Optimizer flattens the param
        #     tree into contiguous padded segments, shards the flat state
        #     + update over the data axis and all-gathers the new weights
        #     in-program (exactly the paper's scheme);
        #   shard_optimizer_state=True: per-leaf axis sharding of the
        #     state tree (coarser — leaves with no divisible dim stay
        #     replicated — but composes with FSDP rules).
        if self._zero1:
            from . import zero1 as _z1mod
            self._opt = _z1mod.Zero1Optimizer(self._opt, self._mesh,
                                              data_axis)
        # zeros_like inside opt.init makes each state leaf inherit its
        # param's sharding (XLA propagates NamedSharding through zeros_like)
        self._opt_state = self._opt.init(self._tr_vals)
        self._shard_opt_state = bool(shard_optimizer_state)
        self._opt_state_shardings = None
        if self._zero1:
            # pin the flat state to P(data) in out_shardings so XLA
            # materializes 1/N state bytes per replica
            self._opt_state_shardings = self._make_state_shardings()
            from . import zero1 as _z1mod
            _telemetry.gauge(
                "mxtpu_optimizer_state_bytes",
                "optimizer-state bytes ONE replica materializes "
                "(replicated state: the full tree; zero1: its 1/N "
                "shard)").set(
                    _z1mod.per_replica_state_bytes(self._opt_state))
            _telemetry.gauge(
                "mxtpu_zero1_allgather_bytes",
                "per-step per-replica inbound all-gather volume the "
                "zero1 weight-update sharding adds").set(
                    _z1mod.zero1_allgather_bytes(self._opt.spec))
        elif self._shard_opt_state:
            self._opt_state_shardings = self._make_state_shardings()
            self._opt_state = jax.tree.map(
                lambda v, s: jax.device_put(v, s),
                self._opt_state, self._opt_state_shardings)
        self._accum = 1 if accum_steps is None else int(accum_steps)
        if self._accum < 1:
            raise MXNetError(f"accum_steps={accum_steps} must be >= 1")
        self._step_count = 0
        self._jit_cache = {}
        # health plane (health.py): per-leaf grad norms / finite mask /
        # update ratios + loss traced as extra step outputs, drained at
        # step boundaries.  Captured at construction so the jit cache
        # never mixes program shapes.
        self._health = _health.HealthMonitor(
            [p.name for p in self._trainable], src="spmd") \
            if _health.enabled() else None
        # device-plane attribution (telemetry_device): report THIS
        # trainer's live optimizer state — zero1: the 1/N flat shard —
        # under owner "optimizer".  weakref so the registration never
        # keeps a discarded trainer's state trees alive.
        wref = _weakref.ref(self)

        def _opt_state_bytes():
            tr = wref()
            if tr is None:
                return 0
            from . import zero1 as _z1mod
            return _z1mod.per_replica_state_bytes(tr._opt_state)

        _telemetry_device.register_owner("optimizer", _opt_state_bytes)

    def _make_state_shardings(self):
        """Per-leaf shardings for the optimizer state: each leaf keeps
        its own inherited sharding (zeros_like in opt.init propagates
        the param's) with the data axis added on the first unsharded,
        divisible dim; leaves already sharded over the data axis (FSDP-
        style rules) are left as they are.  Under zero1 every state leaf
        is a flat padded segment — always P(data)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self._zero1:
            return self._opt.state_shardings(self._opt_state)
        n = self._mesh.shape[self._data_axis]

        def _axes_in(entry):
            if entry is None:
                return ()
            return entry if isinstance(entry, tuple) else (entry,)

        def leaf_sharding(v):
            base = getattr(v, "sharding", None)
            spec = list(base.spec) if base is not None \
                and hasattr(base, "spec") else []
            spec += [None] * (v.ndim - len(spec))
            used = {a for e in spec for a in _axes_in(e)}
            if self._data_axis not in used:
                for d in range(v.ndim):
                    if spec[d] is None and v.shape[d] > 0 \
                            and v.shape[d] % n == 0:
                        spec[d] = self._data_axis
                        break
            return NamedSharding(self._mesh, P(*spec))
        import jax
        return jax.tree.map(leaf_sharding, self._opt_state)

    def _state_out_shardings(self):
        """out_shardings for the optimizer-state output of the step
        program.  When no sharding policy pinned them (plain replicated
        runs: ``_opt_state_shardings is None``) the state must still
        leave the program with the SAME shardings it entered with: the
        state is donated, and with the output left unconstrained GSPMD
        is free to shard any data-axis-divisible leaf — the donated
        (replicated) input buffer then cannot alias the (sharded)
        output and XLA rejects the executable (seen with BN-channel-
        sized momentum leaves, 64 % 8 == 0)."""
        if self._opt_state_shardings is not None:
            return self._opt_state_shardings
        import jax
        try:
            return jax.tree.map(lambda v: v.sharding, self._opt_state)
        except AttributeError:
            return None

    # ------------------------------------------------------------------
    @property
    def mesh(self):
        return self._mesh

    @property
    def params(self) -> Dict[str, object]:
        return {p.name: v
                for p, v in zip(self._trainable, self._tr_vals)}

    def _make_loss_of(self):
        """The per-(micro)batch loss as a pure function of trainable and
        aux values — the trace core shared by the per-step program, the
        accumulation scan, and CompiledLoop's k-step chunk program."""
        import jax.numpy as jnp
        net, loss_blk = self._net, self._loss
        trainable, aux = self._trainable, self._aux

        def loss_of(tr, aux_cur, rng_i, xs, label):
            nds = [NDArray(b) for b in xs]
            out_vals, new_aux = functional_call(
                net, trainable, tr, aux, aux_cur, nds, True, rng_i)
            # multi-output nets (e.g. MLM+NSP heads) pass every output
            # to the loss block: loss(out0, out1, ..., label)
            out_nds = [NDArray(v) for v in out_vals]
            with_label = NDArray(label)
            from .. import autograd as _ag
            with _ag.pause(train_mode=True):
                loss_nd = loss_blk(*out_nds, with_label)
            loss = jnp.mean(loss_nd._data)
            return loss, tuple(new_aux)

        return loss_of

    def _make_grad_fn(self):
        """loss+grad of one FULL batch (microbatch-accumulated when
        accum_steps > 1) as a pure function
        ``grad_of(tr_vals, aux_vals, rng, xs, label) ->
        (loss, new_aux, grads)`` — everything in a train step except the
        optimizer update, so per-step and k-step-chunk programs share one
        definition."""
        import jax
        import jax.numpy as jnp
        loss_of = self._make_loss_of()
        k = self._accum

        def grad_of(tr_vals, aux_vals, rng, xs, label):
            if k == 1:
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(tr_vals, aux_vals, rng, xs,
                                           label)
            else:
                # gradient accumulation: grads computed and consumed
                # PER microbatch inside the scan body, so activation
                # memory is one microbatch's, not the whole batch's —
                # the point of accumulation.  Microbatches interleave
                # (reshape + leading-axis swap) so each one spans every
                # data shard evenly.
                def mb_split(a):
                    rest = a.shape[1:]
                    return a.reshape(a.shape[0] // k, k, *rest).swapaxes(
                        0, 1)

                xs_mb = [mb_split(x) for x in xs]
                label_mb = mb_split(label)
                g0 = jax.tree.map(jnp.zeros_like, tr_vals)

                def micro(carry, mb):
                    g_acc, aux_cur, loss_acc, rng_cur = carry
                    *mb_xs, mb_label = mb
                    rng_i, rng_next = jax.random.split(rng_cur)
                    (l, new_aux), g = jax.value_and_grad(
                        loss_of, has_aux=True)(tr_vals, aux_cur, rng_i,
                                               mb_xs, mb_label)
                    g_acc = jax.tree.map(jnp.add, g_acc, g)
                    return (g_acc, new_aux, loss_acc + l, rng_next), None

                (g_sum, new_aux, loss_sum, _), _ = jax.lax.scan(
                    micro, (g0, aux_vals, jnp.zeros((), jnp.float32),
                            rng),
                    tuple(xs_mb) + (label_mb,))
                grads = jax.tree.map(lambda g: g / k, g_sum)
                loss = loss_sum / k
            return loss, new_aux, grads

        return grad_of

    def _build_step(self):
        import jax
        opt = self._opt
        grad_of = self._make_grad_fn()
        health_on = self._health is not None

        def pure_step(tr_vals, aux_vals, opt_state, step, rng, *batch):
            *xs, label = batch
            loss, new_aux, grads = grad_of(tr_vals, aux_vals, rng, xs,
                                           label)
            new_tr, new_opt = opt.update(tr_vals, grads, opt_state, step)
            if health_on:
                h = _health.train_step_health(list(grads), list(tr_vals),
                                              list(new_tr), loss=loss)
                return loss, new_tr, new_aux, new_opt, h
            return loss, new_tr, new_aux, new_opt

        donate = (0, 1, 2) if self._donate else ()
        outsh = (None, self._tr_shardings, self._aux_shardings,
                 self._state_out_shardings())
        if health_on:
            outsh += (None,)
        return _telemetry.instrument_jit("spmd", jax.jit(
            pure_step, out_shardings=outsh, donate_argnums=donate))

    def _shard_batch(self, arr):
        import jax
        if isinstance(arr, NDArray):
            arr = arr._data
        sharding = mesh_mod.named_sharding(self._mesh, self._data_axis)
        if jax.process_count() > 1:
            # multi-host: each process feeds its LOCAL batch shard; the
            # global array is assembled across processes (DCN path —
            # reference analog: each dist worker computes on its own
            # partition, kvstore_dist.h)
            import numpy as _np
            return jax.make_array_from_process_local_data(
                sharding, _np.asarray(arr))
        return jax.device_put(arr, sharding)

    def step(self, *batch) -> float:
        """Run one train step; returns the (replicated) scalar loss as a
        jax array (non-blocking — async dispatch)."""
        observe = bool(_telemetry.TRAINER.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        with _telemetry.trace_span("spmd.step", cat="trainer"):
            out = self._step_impl(*batch)
        if observe:
            _telemetry.TRAINER.publish(
                phase="step", seconds=_time.perf_counter() - t0)
        return out

    def _step_impl(self, *batch):
        from .. import random as _random
        import jax.numpy as jnp
        with _telemetry.trace_span("spmd.shard_batch", cat="transfer"):
            sharded = tuple(self._shard_batch(b) for b in batch)
        if self._accum > 1:
            B = sharded[0].shape[0]
            dp = self._mesh.shape[self._data_axis]
            if B % (self._accum * dp):
                raise MXNetError(
                    f"global batch {B} must divide by accum_steps "
                    f"{self._accum} x data axis {dp} for even "
                    "microbatch sharding")
        key = self._build_key(sharded)
        if key not in self._jit_cache:
            self._jit_cache[key] = self._build_step()
        self._step_count += 1
        step_arr = jnp.asarray(self._step_count, jnp.int32)
        rng = _random.new_key()
        if self._health is not None:
            loss, self._tr_vals, self._aux_vals, self._opt_state, hst = \
                self._jit_cache[key](self._tr_vals, self._aux_vals,
                                     self._opt_state, step_arr, rng,
                                     *sharded)
            # queued device stats; drained only when already finished
            self._health.submit(self._step_count - 1, 1, hst)
        else:
            loss, self._tr_vals, self._aux_vals, self._opt_state = \
                self._jit_cache[key](self._tr_vals, self._aux_vals,
                                     self._opt_state, step_arr, rng,
                                     *sharded)
        # the whole step (fwd + bwd + update) is ONE compiled program
        _telemetry.gauge("mxtpu_optimizer_dispatches_per_step").set(1)
        return loss

    def _build_key(self, arrs):
        return tuple((a.shape, str(a.dtype)) for a in arrs)

    def sync_to_block(self):
        """Copy current parameter/aux values back into the Block's
        Parameters, gathered onto each Parameter's own device so eager
        execution keeps working."""
        import jax
        if self._health is not None:
            self._health.sync()
        fetch = _fetch_full
        for p, v in zip(self._trainable, self._tr_vals):
            dev = p.data().ctx.jax_device()
            p._data._set_data(jax.device_put(fetch(v), dev))
        for p, v in zip(self._aux, self._aux_vals):
            dev = p.data().ctx.jax_device()
            p._data._set_data(jax.device_put(fetch(v), dev))

    def reload_params(self):
        """Re-place parameter/aux values from the Block's current
        Parameters — the inverse of :meth:`sync_to_block`, used after a
        checkpoint restore wrote fresh arrays into the net
        (``AsyncCheckpointer.restore_into``) so the compiled step resumes
        from the restored weights."""
        self._tr_vals = tuple(
            _placed_copy(p.data()._data, s)
            for p, s in zip(self._trainable, self._tr_shardings))
        self._aux_vals = tuple(
            _placed_copy(p.data()._data, s)
            for p, s in zip(self._aux, self._aux_shardings))
