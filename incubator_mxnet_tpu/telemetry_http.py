"""Live telemetry HTTP exporter — a stdlib-only ``http.server`` running
on a daemon thread, so a training process can be inspected from outside
while it runs:

* ``GET /metrics``  — Prometheus text exposition of the metrics registry
  (the same bytes :func:`telemetry.render_prometheus` writes), ready to
  be scraped.
* ``GET /healthz``  — liveness probe; JSON with collector state + uptime.
* ``GET /trace``    — the span tracer's current tree (open roots with
  running durations + recent finished roots) as JSON.

Start it with ``MXNET_TELEMETRY_PORT=<port>`` (telemetry import tail),
``mxtpu-stats --serve`` (CLI), or :func:`start_server` directly.  Port 0
binds an ephemeral port — :func:`start_server` returns the server object
whose ``server_address[1]`` is the bound port (used by the tests).

The telemetry module is imported lazily inside the handlers: this module
is imported from telemetry's own tail, and the late import keeps the two
acyclic at import time.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

__all__ = ["start_server", "stop_server", "server"]

_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None
_t_start: Optional[float] = None
_lock = threading.Lock()


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxtpu-telemetry/1.0"

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (http.server API)
        from . import telemetry
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path in ("/metrics", "/"):
                self._send(200, telemetry.render_prometheus(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                self._send(200, json.dumps({
                    "status": "ok",
                    "collecting": telemetry.enabled(),
                    "tracing": telemetry.tracer.active,
                    "uptime_s": None if _t_start is None
                    else round(time.time() - _t_start, 3),
                }) + "\n", "application/json")
            elif path == "/trace":
                self._send(200,
                           json.dumps(telemetry.tracer.tree(), indent=2,
                                      default=str) + "\n",
                           "application/json")
            else:
                self._send(404, "not found: try /metrics /healthz /trace\n",
                           "text/plain; charset=utf-8")
        except Exception as e:          # an exporter bug must not 500-loop
            try:
                self._send(500, f"exporter error: {e!r}\n",
                           "text/plain; charset=utf-8")
            except Exception:
                pass

    def log_message(self, fmt, *args):
        pass                            # stay silent on training stdout


def start_server(port: int, host: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Start (or return the already-running) exporter on ``host:port`` in
    a daemon thread.  Raises ``OSError`` if the port cannot be bound."""
    global _server, _thread, _t_start
    with _lock:
        if _server is not None:
            return _server
        srv = ThreadingHTTPServer((host, int(port)), _Handler)
        srv.daemon_threads = True
        th = threading.Thread(target=srv.serve_forever,
                              name="mxtpu-telemetry-http", daemon=True)
        th.start()
        _server, _thread, _t_start = srv, th, time.time()
        return srv


def stop_server() -> None:
    """Shut the exporter down and release the port (no-op when idle)."""
    global _server, _thread, _t_start
    with _lock:
        srv, th = _server, _thread
        _server = _thread = _t_start = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if th is not None:
        th.join(timeout=5)


def server() -> Optional[ThreadingHTTPServer]:
    """The running server object (``server_address[1]`` is the bound
    port), or None."""
    return _server
