"""Live telemetry HTTP exporter — a stdlib-only ``http.server`` running
on a daemon thread, so a training process can be inspected from outside
while it runs:

* ``GET /metrics``  — Prometheus text exposition of the metrics registry
  (the same bytes :func:`telemetry.render_prometheus` writes), ready to
  be scraped.  Serving metrics (``mxtpu_serve_*``) appear here the
  moment ``serving`` is imported — the registry is shared, no wiring.
* ``GET /healthz``  — liveness probe; JSON with collector state + uptime.
* ``GET /trace``    — the span tracer's current tree (open roots with
  running durations + recent finished roots) as JSON — BOUNDED:
  ``?limit=N`` caps finished roots (default 32, max 256), ``?since=S``
  keeps only roots that started in the last S seconds, and
  ``?request_id=RID`` looks up the spans carrying that request id (the
  per-request lookup behind the serving plane's request tracing;
  docs/observability.md).  A long-running server can no longer emit a
  multi-MB tree by default.
* ``GET /slo``      — per-model SLO state when the serving plane is
  loaded (``{"models": {}}`` otherwise; the route never *imports*
  serving — a telemetry scrape must not drag jax/engine code in).

Start it with ``MXNET_TELEMETRY_PORT=<port>`` (telemetry import tail),
``mxtpu-stats --serve`` (CLI), or :func:`start_server` directly.  Port 0
binds an ephemeral port — :func:`start_server` returns the server object
whose ``server_address[1]`` is the bound port (used by the tests).

The HTTP plumbing (response helpers, silent logging, daemon-thread
lifecycle) lives in :mod:`incubator_mxnet_tpu.http_util`, shared with
the model server (``serving/server.py``) so the two stacks can't drift.
The telemetry module is imported lazily inside the handlers: this module
is imported from telemetry's own tail, and the late import keeps the two
acyclic at import time.
"""
from __future__ import annotations

import json
import sys
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional

from .http_util import BaseJSONHandler, start_http_server, stop_http_server

__all__ = ["start_server", "stop_server", "server", "trace_body",
           "slo_body", "flight_body", "metrics_state_body"]

#: ``/trace`` bounds: default and hard cap for ``?limit=``
TRACE_DEFAULT_LIMIT = 32
TRACE_MAX_LIMIT = 256


def _param(params: dict, key: str) -> Optional[str]:
    vals = params.get(key)
    return vals[-1] if vals else None


def trace_body(params: dict) -> dict:
    """The bounded ``/trace`` response body, shared by this exporter and
    the model server's route.  ``params`` is a ``parse_qs`` dict;
    recognized: ``limit`` (finished roots, default 32, clamped to
    [0, 256]), ``since`` (seconds of lookback), ``request_id`` (span
    lookup by the ``request_id`` attr — returns the matching spans'
    subtrees instead of the whole forest)."""
    from . import telemetry
    rid = _param(params, "request_id")
    try:
        limit = int(_param(params, "limit") or TRACE_DEFAULT_LIMIT)
    except ValueError:
        limit = TRACE_DEFAULT_LIMIT
    limit = max(0, min(limit, TRACE_MAX_LIMIT))
    if rid:
        return {"request_id": rid,
                "spans": telemetry.tracer.find_spans(
                    "request_id", rid, limit=limit or TRACE_DEFAULT_LIMIT)}
    since = None
    raw_since = _param(params, "since")
    if raw_since:
        try:
            since = float(raw_since)
        except ValueError:
            since = None
    return telemetry.tracer.tree(max_finished=limit, since=since)


def flight_body(reason: str = "http") -> dict:
    """The ``/flight`` response body: the flight recorder's full
    postmortem payload (ring + metrics + providers) WITHOUT writing a
    dump file — the router pulls this view of an implicated replica into
    a fleet incident bundle."""
    from . import telemetry_ring
    return telemetry_ring.recorder.payload(reason)


def metrics_state_body() -> dict:
    """The ``/metrics.json`` response body: the registry's mergeable
    export (per-label counter/gauge values + raw histogram reservoirs),
    the feed behind the router's federated ``/metrics``."""
    from . import telemetry
    return telemetry.registry.export_state()


def slo_body() -> dict:
    """The ``/slo`` response body.  Reads the tracker only when the
    serving plane is already in ``sys.modules`` — a metrics exporter
    must never be the thing that imports jax/engine code."""
    slo = sys.modules.get("incubator_mxnet_tpu.serving.slo")
    if slo is None:
        return {"objectives": {}, "models": {}}
    return slo.tracker.snapshot()

_server: Optional[ThreadingHTTPServer] = None
_t_start: Optional[float] = None
_lock = threading.Lock()


class _Handler(BaseJSONHandler):
    server_version = "mxtpu-telemetry/1.0"

    def do_GET(self):  # noqa: N802 (http.server API)
        self.guard(self._route)

    def _route(self):
        from urllib.parse import parse_qs, urlsplit
        from . import telemetry
        split = urlsplit(self.path)
        params = parse_qs(split.query)
        path = split.path.rstrip("/") or "/"
        if path in ("/metrics", "/"):
            self._send(200, telemetry.render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/metrics.json":
            self._send(200,
                       json.dumps(metrics_state_body(), default=str)
                       + "\n", "application/json")
        elif path == "/flight":
            self._send(200,
                       json.dumps(flight_body(), default=str) + "\n",
                       "application/json")
        elif path == "/healthz":
            self._send(200, json.dumps({
                "status": "ok",
                "collecting": telemetry.enabled(),
                "tracing": telemetry.tracer.active,
                "uptime_s": None if _t_start is None
                else round(time.time() - _t_start, 3),
            }) + "\n", "application/json")
        elif path == "/trace":
            self._send(200,
                       json.dumps(trace_body(params), indent=2,
                                  default=str) + "\n",
                       "application/json")
        elif path == "/slo":
            self._send(200,
                       json.dumps(slo_body(), default=str) + "\n",
                       "application/json")
        else:
            self._send(404, "not found: try /metrics /metrics.json "
                            "/healthz /trace /slo /flight\n",
                       "text/plain; charset=utf-8")


def start_server(port: int, host: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Start (or return the already-running) exporter on ``host:port`` in
    a daemon thread.  Raises ``OSError`` if the port cannot be bound."""
    global _server, _t_start
    with _lock:
        if _server is not None:
            return _server
        srv = start_http_server(_Handler, port, host,
                                name="mxtpu-telemetry-http")
        _server, _t_start = srv, time.time()
        return srv


def stop_server() -> None:
    """Shut the exporter down and release the port (no-op when idle)."""
    global _server, _t_start
    with _lock:
        srv = _server
        _server = _t_start = None
    stop_http_server(srv)


def server() -> Optional[ThreadingHTTPServer]:
    """The running server object (``server_address[1]`` is the bound
    port), or None."""
    return _server
