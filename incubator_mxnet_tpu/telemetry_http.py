"""Live telemetry HTTP exporter — a stdlib-only ``http.server`` running
on a daemon thread, so a training process can be inspected from outside
while it runs:

* ``GET /metrics``  — Prometheus text exposition of the metrics registry
  (the same bytes :func:`telemetry.render_prometheus` writes), ready to
  be scraped.  Serving metrics (``mxtpu_serve_*``) appear here the
  moment ``serving`` is imported — the registry is shared, no wiring.
* ``GET /healthz``  — liveness probe; JSON with collector state + uptime.
* ``GET /trace``    — the span tracer's current tree (open roots with
  running durations + recent finished roots) as JSON.

Start it with ``MXNET_TELEMETRY_PORT=<port>`` (telemetry import tail),
``mxtpu-stats --serve`` (CLI), or :func:`start_server` directly.  Port 0
binds an ephemeral port — :func:`start_server` returns the server object
whose ``server_address[1]`` is the bound port (used by the tests).

The HTTP plumbing (response helpers, silent logging, daemon-thread
lifecycle) lives in :mod:`incubator_mxnet_tpu.http_util`, shared with
the model server (``serving/server.py``) so the two stacks can't drift.
The telemetry module is imported lazily inside the handlers: this module
is imported from telemetry's own tail, and the late import keeps the two
acyclic at import time.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Optional

from .http_util import BaseJSONHandler, start_http_server, stop_http_server

__all__ = ["start_server", "stop_server", "server"]

_server: Optional[ThreadingHTTPServer] = None
_t_start: Optional[float] = None
_lock = threading.Lock()


class _Handler(BaseJSONHandler):
    server_version = "mxtpu-telemetry/1.0"

    def do_GET(self):  # noqa: N802 (http.server API)
        self.guard(self._route)

    def _route(self):
        from . import telemetry
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path in ("/metrics", "/"):
            self._send(200, telemetry.render_prometheus(),
                       "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            self._send(200, json.dumps({
                "status": "ok",
                "collecting": telemetry.enabled(),
                "tracing": telemetry.tracer.active,
                "uptime_s": None if _t_start is None
                else round(time.time() - _t_start, 3),
            }) + "\n", "application/json")
        elif path == "/trace":
            self._send(200,
                       json.dumps(telemetry.tracer.tree(), indent=2,
                                  default=str) + "\n",
                       "application/json")
        else:
            self._send(404, "not found: try /metrics /healthz /trace\n",
                       "text/plain; charset=utf-8")


def start_server(port: int, host: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Start (or return the already-running) exporter on ``host:port`` in
    a daemon thread.  Raises ``OSError`` if the port cannot be bound."""
    global _server, _t_start
    with _lock:
        if _server is not None:
            return _server
        srv = start_http_server(_Handler, port, host,
                                name="mxtpu-telemetry-http")
        _server, _t_start = srv, time.time()
        return srv


def stop_server() -> None:
    """Shut the exporter down and release the port (no-op when idle)."""
    global _server, _t_start
    with _lock:
        srv = _server
        _server = _t_start = None
    stop_http_server(srv)


def server() -> Optional[ThreadingHTTPServer]:
    """The running server object (``server_address[1]`` is the bound
    port), or None."""
    return _server
