"""Image ops + augmenters + ImageIter (reference:
python/mxnet/image/image.py).

Design: images are HWC NDArrays.  Decode is PIL (the reference links
OpenCV; output bytes→pixels is codec-standard either way).  Resize is
``jax.image.resize`` so augmentation pipelines can run jitted on device
when batched; the per-sample eager path stays cheap on CPU feed workers.
"""
from __future__ import annotations

import os
import random as _pyrandom

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray

__all__ = [
    "imdecode", "imread", "imresize", "resize_short", "fixed_crop",
    "center_crop", "random_crop", "random_size_crop", "color_normalize",
    "scale_down", "copyMakeBorder",
    "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
    "ForceResizeAug", "CastAug", "RandomCropAug", "RandomSizedCropAug",
    "CenterCropAug", "BrightnessJitterAug", "ContrastJitterAug",
    "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
    "LightingAug", "ColorNormalizeAug", "RandomGrayAug",
    "HorizontalFlipAug", "CreateAugmenter", "ImageIter",
]

_INTERP_METHODS = {0: "nearest", 1: "linear", 2: "cubic", 3: "linear",
                   4: "lanczos3", 9: "cubic", 10: "linear"}


def _to_nd(arr) -> NDArray:
    return arr if isinstance(arr, NDArray) else nd.array(arr)


def imdecode(buf, flag=1, to_rgb=1, out=None) -> NDArray:
    """Decode an encoded (JPEG/PNG/...) byte buffer to an HWC uint8
    NDArray (reference: image.imdecode over cv2.imdecode)."""
    import io as _io
    from PIL import Image
    if isinstance(buf, NDArray):
        buf = bytes(bytearray(buf.asnumpy().astype(_np.uint8)))
    pil = Image.open(_io.BytesIO(buf))
    if flag == 0:
        pil = pil.convert("L")
        arr = _np.asarray(pil)[:, :, None]
    else:
        pil = pil.convert("RGB")
        arr = _np.asarray(pil)
        if not to_rgb:      # cv2-style BGR out
            arr = arr[:, :, ::-1]
    res = nd.array(arr.astype(_np.uint8), dtype=_np.uint8)
    if out is not None:
        out[:] = res
        return out
    return res


def imread(filename, flag=1, to_rgb=1) -> NDArray:
    """Read an image file (reference: image.imread)."""
    if not os.path.isfile(filename):
        raise MXNetError(f"imread: no such file {filename!r}")
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imresize(src, w, h, interp=2) -> NDArray:
    """Resize HWC image to (h, w) (reference: image.imresize)."""
    import jax
    s = _to_nd(src)
    method = _INTERP_METHODS.get(interp, "linear")
    out = jax.image.resize(
        s._data.astype("float32"), (h, w, s.shape[2]), method=method)
    if _np.dtype(s.dtype) == _np.uint8:
        import jax.numpy as jnp
        out = jnp.clip(jnp.round(out), 0, 255).astype("uint8")
    else:
        out = out.astype(s.dtype)
    return NDArray(out)


def resize_short(src, size, interp=2) -> NDArray:
    """Resize shorter edge to ``size`` keeping aspect (reference:
    image.resize_short)."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = int(h * size / w), size
    else:
        new_h, new_w = size, int(w * size / h)
    return imresize(src, new_w, new_h, interp)


def scale_down(src_size, size):
    """Shrink the crop size (w, h) proportionally to fit inside
    src_size if it overflows (reference: image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def copyMakeBorder(src, top, bot, left, right, type=0, values=0.0):
    """Pad an image with a constant border (reference:
    mx.image.copyMakeBorder, cv2-compatible; only BORDER_CONSTANT
    ``type=0`` is meaningful on this backend).  ``values`` is a scalar
    or a per-channel fill color."""
    if type != 0:
        raise MXNetError(
            "copyMakeBorder: only type=0 (constant border) is supported")
    s = _to_nd(src)

    def fn(x):
        import jax.numpy as jnp
        pad = [(top, bot), (left, right)] + [(0, 0)] * (x.ndim - 2)
        vals = _np.asarray(values, _np.float32).reshape(-1)
        if vals.size == 1:
            return jnp.pad(x, pad, constant_values=float(vals[0]))
        if x.ndim < 3 or vals.size != x.shape[2]:
            raise MXNetError(
                f"copyMakeBorder: values has {vals.size} entries but "
                f"image has {x.shape[2] if x.ndim >= 3 else 1} channels")
        out = jnp.pad(x, pad)
        h, w = x.shape[0], x.shape[1]
        iy = jnp.arange(out.shape[0])
        ix = jnp.arange(out.shape[1])
        border = ~((iy[:, None] >= top) & (iy[:, None] < top + h)
                   & (ix[None, :] >= left) & (ix[None, :] < left + w))
        fill = jnp.asarray(vals, x.dtype)[None, None, :]
        return jnp.where(border[..., None], fill, out)
    from ..ndarray.ndarray import _invoke
    return _invoke(fn, [s], name="copyMakeBorder")


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    """Crop a fixed region, optionally resizing to ``size`` (w, h)
    (reference: image.fixed_crop)."""
    s = _to_nd(src)
    out = NDArray(s._data[y0:y0 + h, x0:x0 + w, :])
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    """Center crop to (w, h) (reference: image.center_crop).  Returns
    (cropped, (x0, y0, w, h))."""
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max(0, (w - new_w) // 2)
    y0 = max(0, (h - new_h) // 2)
    cw, ch = min(new_w, w), min(new_h, h)
    out = fixed_crop(src, x0, y0, cw, ch, size, interp)
    return out, (x0, y0, cw, ch)


def random_crop(src, size, interp=2):
    """Random crop to (w, h), upscaling first if needed (reference:
    image.random_crop)."""
    h, w = src.shape[:2]
    new_w, new_h = size
    if w < new_w or h < new_h:
        src = resize_short(src, max(new_w, new_h), interp)
        h, w = src.shape[:2]
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, None, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop, resized to (w, h) (reference:
    image.random_size_crop — the inception-style crop)."""
    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = _pyrandom.uniform(*area) * src_area
        log_ratio = (_np.log(ratio[0]), _np.log(ratio[1]))
        aspect = _np.exp(_pyrandom.uniform(*log_ratio))
        new_w = int(round(_np.sqrt(target_area * aspect)))
        new_h = int(round(_np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None) -> NDArray:
    """(src - mean) / std over the channel dim (reference:
    image.color_normalize)."""
    s = _to_nd(src)
    data = s._data.astype("float32")
    mean_a = mean._data if isinstance(mean, NDArray) else _np.asarray(
        mean, _np.float32)
    data = data - mean_a
    if std is not None:
        std_a = std._data if isinstance(std, NDArray) else _np.asarray(
            std, _np.float32)
        data = data / std_a
    return NDArray(data)


# ---------------------------------------------------------------------------
# augmenters (reference: image.py Augmenter hierarchy)
# ---------------------------------------------------------------------------
class Augmenter:
    """Image augmenter base (reference: image.Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        _pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _to_nd(src).astype(self.typ)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return NDArray(_to_nd(src)._data * alpha)


class ContrastJitterAug(Augmenter):
    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        import jax.numpy as jnp
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        data = _to_nd(src)._data
        gray = (data * self._coef).sum(axis=-1, keepdims=True)
        mean = jnp.mean(gray)
        return NDArray(data * alpha + mean * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = _np.array([0.299, 0.587, 0.114], _np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        data = _to_nd(src)._data
        gray = (data * self._coef).sum(axis=-1, keepdims=True)
        return NDArray(data * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """Rotate hue via the YIQ transform trick (reference:
    image.HueJitterAug)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], _np.float32)
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], _np.float32)

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u, w_ = _np.cos(alpha * _np.pi), _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0], [0.0, u, -w_], [0.0, w_, u]],
                       _np.float32)
        t = self.ityiq @ bt @ self.tyiq
        data = _to_nd(src)._data
        return NDArray(data @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-noise lighting (reference: image.LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval, _np.float32)
        self.eigvec = _np.asarray(eigvec, _np.float32)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,)).astype(
            _np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return NDArray(_to_nd(src)._data + rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    _coef = _np.array([[0.299], [0.587], [0.114]], _np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            data = _to_nd(src)._data
            gray = data @ self._coef
            import jax.numpy as jnp
            return NDArray(jnp.broadcast_to(gray, data.shape))
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return NDArray(_to_nd(src)._data[:, ::-1, :])
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Standard augmenter pipeline factory (reference:
    image.CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ---------------------------------------------------------------------------
# ImageIter (reference: image.ImageIter — Python-side iterator over .rec
# or an image list + root dir)
# ---------------------------------------------------------------------------
class ImageIter:
    """Image iterator with pluggable augmenters, over a RecordIO pack
    (``path_imgrec``) or an image list (``path_imglist``/``imglist`` +
    ``path_root``) (reference: image.ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label",
                 last_batch_handle="pad", **kwargs):
        from ..io.io import DataDesc
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (channels, H, W)")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._record = None
        self.imglist = {}
        self.seq = []

        if path_imgrec is not None:
            from ..io.recordio import MXIndexedRecordIO
            if path_imgidx is None:
                path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
            if not os.path.isfile(path_imgidx):
                raise MXNetError(
                    "ImageIter over .rec needs the .idx sidecar "
                    f"({path_imgidx} missing) — pack with im2rec")
            self._record = MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self.seq = list(self._record.keys)
        elif path_imglist is not None or imglist is not None:
            if imglist is None:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) < 3:
                            continue
                        key = int(parts[0])
                        label = _np.array(parts[1:-1], _np.float32)
                        self.imglist[key] = (label, parts[-1])
                        self.seq.append(key)
            else:
                for i, item in enumerate(imglist):
                    label = _np.asarray(item[0], dtype=_np.float32) \
                        if not _np.isscalar(item[0]) \
                        else _np.array([item[0]], _np.float32)
                    self.imglist[i] = (label, item[1])
                    self.seq.append(i)
            self.path_root = path_root
        else:
            raise MXNetError("ImageIter needs path_imgrec, path_imglist "
                             "or imglist")

        if num_parts > 1:   # sharded input partitioning, reference parity
            self.seq = self.seq[part_index::num_parts]
        self.auglist = (CreateAugmenter(data_shape, **kwargs)
                        if aug_list is None else aug_list)
        self.provide_data = [DataDesc(
            data_name, (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc(
            label_name, (batch_size,) if label_width == 1
            else (batch_size, label_width))]
        self.reset()

    def __iter__(self):
        return self

    def reset(self):
        if self.shuffle:
            _pyrandom.shuffle(self.seq)
        self._cursor = 0

    def next_sample(self):
        """Return (label, decoded HWC image NDArray)."""
        if self._cursor >= len(self.seq):
            raise StopIteration
        key = self.seq[self._cursor]
        self._cursor += 1
        if self._record is not None:
            from ..io.recordio import unpack
            header, payload = unpack(self._record.read_idx(key))
            return header.label, imdecode(payload)
        label, fname = self.imglist[key]
        return label, imread(os.path.join(self.path_root, fname))

    def next(self):
        from ..io.io import DataBatch
        C, H, W = self.data_shape
        data = _np.zeros((self.batch_size, C, H, W), _np.float32)
        label = _np.zeros((self.batch_size, self.label_width), _np.float32)
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                lab, img = self.next_sample()
                for aug in self.auglist:
                    img = aug(img)
                arr = img.asnumpy()
                if arr.shape[:2] != (H, W):
                    raise MXNetError(
                        f"augmented image is {arr.shape[:2]}, expected "
                        f"{(H, W)} — add a crop/resize augmenter")
                data[i] = arr.transpose(2, 0, 1)[:C]
                lab = _np.atleast_1d(_np.asarray(lab, _np.float32))
                label[i, :min(self.label_width, lab.size)] = \
                    lab[:self.label_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
            if self.last_batch_handle == "discard":
                raise
        lab_out = label[:, 0] if self.label_width == 1 else label
        return DataBatch(data=[nd.array(data)], label=[nd.array(lab_out)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def __next__(self):
        return self.next()
