"""Detection-task image iterator + augmenters (reference:
python/mxnet/image/detection.py).

Labels are object lists ``(N, 4+) [cls, x0, y0, x1, y1, ...]`` in
normalized corner coordinates; augmenters transform image and boxes
together.  The iterator pads labels to a fixed ``label_shape`` so batch
shapes stay static — exactly what XLA wants (SURVEY §7.2-4).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ..base import MXNetError
from .. import ndarray as nd
from .image import (Augmenter, CastAug, ColorJitterAug, ForceResizeAug,
                    HueJitterAug, ImageIter, LightingAug,
                    RandomGrayAug, color_normalize, imresize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base: __call__(src, label) -> (src, label)
    (reference: detection.DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter (reference: detection.DetBorrowAug)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug needs an image Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of several augmenters (reference:
    detection.DetRandomSelectAug)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return _pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _pyrandom.random() < self.p:
            from ..ndarray.ndarray import NDArray
            src = NDArray(src._data[:, ::-1, :])
            valid = label[:, 0] >= 0
            x0 = label[:, 1].copy()
            label[:, 1] = _np.where(valid, 1.0 - label[:, 3], label[:, 1])
            label[:, 3] = _np.where(valid, 1.0 - x0, label[:, 3])
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping min IoU with gt boxes (reference:
    detection.DetRandomCropAug — SSD-style sampler)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        super().__init__(min_object_covered=min_object_covered)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        h, w = src.shape[:2]
        for _ in range(self.max_attempts):
            area = _pyrandom.uniform(*self.area_range)
            ar = _pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, _np.sqrt(area * ar))
            ch = min(1.0, _np.sqrt(area / ar))
            cx0 = _pyrandom.uniform(0, 1 - cw)
            cy0 = _pyrandom.uniform(0, 1 - ch)
            crop = _np.array([cx0, cy0, cx0 + cw, cy0 + ch], _np.float32)
            valid = label[:, 0] >= 0
            if not valid.any():
                break
            boxes = label[valid, 1:5]
            ix0 = _np.maximum(boxes[:, 0], crop[0])
            iy0 = _np.maximum(boxes[:, 1], crop[1])
            ix1 = _np.minimum(boxes[:, 2], crop[2])
            iy1 = _np.minimum(boxes[:, 3], crop[3])
            inter = _np.clip(ix1 - ix0, 0, None) * \
                _np.clip(iy1 - iy0, 0, None)
            box_area = (boxes[:, 2] - boxes[:, 0]) * \
                (boxes[:, 3] - boxes[:, 1])
            cover = inter / _np.clip(box_area, 1e-12, None)
            if (cover >= self.min_object_covered).any():
                keep = cover >= self.min_object_covered
                new_label = _np.full_like(label, -1.0)
                kept = label[valid][keep].copy()
                # re-express kept boxes in crop coordinates, clipped
                kept[:, 1] = _np.clip((kept[:, 1] - crop[0]) / cw, 0, 1)
                kept[:, 2] = _np.clip((kept[:, 2] - crop[1]) / ch, 0, 1)
                kept[:, 3] = _np.clip((kept[:, 3] - crop[0]) / cw, 0, 1)
                kept[:, 4] = _np.clip((kept[:, 4] - crop[1]) / ch, 0, 1)
                new_label[:kept.shape[0]] = kept
                x0p, y0p = int(crop[0] * w), int(crop[1] * h)
                x1p, y1p = int(crop[2] * w), int(crop[3] * h)
                from ..ndarray.ndarray import NDArray
                src = NDArray(src._data[y0p:y1p, x0p:x1p, :])
                return src, new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Random expand-pad (zoom out) (reference:
    detection.DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(area_range=area_range)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray
        h, w = src.shape[:2]
        scale = _pyrandom.uniform(*self.area_range)
        if scale <= 1.0:
            return src, label
        new_h, new_w = int(h * _np.sqrt(scale)), int(w * _np.sqrt(scale))
        y0 = _pyrandom.randint(0, new_h - h)
        x0 = _pyrandom.randint(0, new_w - w)
        canvas = jnp.broadcast_to(
            jnp.asarray(self.pad_val, src._data.dtype),
            (new_h, new_w, 3)).copy()
        canvas = canvas.at[y0:y0 + h, x0:x0 + w, :].set(src._data)
        label = label.copy()
        valid = label[:, 0] >= 0
        label[:, 1] = _np.where(valid, (label[:, 1] * w + x0) / new_w,
                                label[:, 1])
        label[:, 2] = _np.where(valid, (label[:, 2] * h + y0) / new_h,
                                label[:, 2])
        label[:, 3] = _np.where(valid, (label[:, 3] * w + x0) / new_w,
                                label[:, 3])
        label[:, 4] = _np.where(valid, (label[:, 4] * h + y0) / new_h,
                                label[:, 4])
        return NDArray(canvas), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, hue=0,
                       pca_noise=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), max_attempts=50,
                       pad_val=(127, 127, 127)):
    """Detection pipeline factory (reference:
    detection.CreateDetAugmenter)."""
    auglist = []
    if resize > 0:
        from .image import ResizeAug
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (1.0, max(1.0, area_range[1])),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and std is not None:
        from .image import ColorNormalizeAug
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator: batches images + fixed-shape object labels
    (reference: detection.ImageDetIter)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, aug_list=None, imglist=None,
                 object_width=5, max_objects=50, **kwargs):
        self.object_width = object_width
        self.max_objects = max_objects
        det_kwargs = {}
        for k in ("resize", "rand_crop", "rand_pad", "rand_gray",
                  "rand_mirror", "mean", "std", "brightness", "contrast",
                  "saturation", "hue", "pca_noise", "inter_method",
                  "min_object_covered", "aspect_ratio_range", "area_range",
                  "max_attempts", "pad_val"):
            if k in kwargs:
                det_kwargs[k] = kwargs.pop(k)
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, aug_list=[], imglist=imglist,
                         **kwargs)
        self.auglist = (CreateDetAugmenter(data_shape, **det_kwargs)
                        if aug_list is None else aug_list)
        from ..io.io import DataDesc
        self.provide_label = [DataDesc(
            "label", (batch_size, max_objects, object_width))]

    def _parse_label(self, label):
        """Reference det-label layout: [header_len, obj_width, ...,
        obj_width * N fields] or already (N, obj_width)."""
        arr = _np.asarray(label, _np.float32).ravel()
        if arr.size >= 2 and arr[0] >= 2 and arr[1] >= 5:
            header_len, width = int(arr[0]), int(arr[1])
            body = arr[header_len:]
            n = body.size // width
            return body[:n * width].reshape(n, width)[:, :self.object_width]
        n = arr.size // self.object_width
        return arr[:n * self.object_width].reshape(n, self.object_width)

    def next(self):
        from ..io.io import DataBatch
        C, H, W = self.data_shape
        data = _np.zeros((self.batch_size, C, H, W), _np.float32)
        labels = _np.full((self.batch_size, self.max_objects,
                           self.object_width), -1.0, _np.float32)
        i = 0
        pad = 0
        try:
            while i < self.batch_size:
                raw_label, img = self.next_sample()
                obj = self._parse_label(raw_label)
                padded = _np.full((self.max_objects, self.object_width),
                                  -1.0, _np.float32)
                padded[:min(len(obj), self.max_objects)] = \
                    obj[:self.max_objects]
                for aug in self.auglist:
                    img, padded = aug(img, padded)
                arr = img.asnumpy()
                if arr.shape[:2] != (H, W):
                    raise MXNetError(
                        f"augmented image is {arr.shape[:2]}, expected "
                        f"{(H, W)} — CreateDetAugmenter adds the resize")
                data[i] = arr.transpose(2, 0, 1)[:C]
                labels[i] = padded
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = self.batch_size - i
        return DataBatch(data=[nd.array(data)], label=[nd.array(labels)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)
