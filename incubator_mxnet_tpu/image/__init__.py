"""``mx.image`` — image decode / resize / crop ops, augmenters, and
ImageIter (reference: python/mxnet/image/image.py, detection.py)."""
from .image import *  # noqa: F401,F403
from . import image
from . import detection
from .detection import ImageDetIter, CreateDetAugmenter  # noqa: F401

__all__ = list(image.__all__) + ["ImageDetIter", "CreateDetAugmenter"]
