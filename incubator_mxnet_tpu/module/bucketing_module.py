"""BucketingModule: variable-length sequence training (reference:
python/mxnet/module/bucketing_module.py).

One Module per bucket key, parameters shared by reference.  On TPU this is
the RIGHT shape for XLA too: each bucket is one static-shape compiled
program (compile-per-bucket, cached), exactly how the reference amortizes
executors per bucket.  Long-context beyond bucketing is the ring-attention
SP path (``parallel.ring``), which the reference lacks."""
from __future__ import annotations

import logging
from typing import Callable, Dict, Optional

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen: Callable, default_bucket_key=None,
                 logger=logging, context=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets: Dict[object, Module] = {}
        self._curr_module: Optional[Module] = None
        self._curr_bucket_key = None
        self._initializer = None

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _gen_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names=data_names,
                      label_names=label_names, logger=self.logger,
                      context=self._context,
                      fixed_param_names=self._fixed_param_names)

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training=for_training,
                 inputs_need_grad=inputs_need_grad, grad_req=grad_req)
        self._buckets[self._default_bucket_key] = mod
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True
        self._grad_req = grad_req
        self._inputs_need_grad = inputs_need_grad

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if not self.binded:
            raise MXNetError("switch_bucket: call bind first")
        if bucket_key == self._curr_bucket_key:
            return
        if bucket_key not in self._buckets:
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes,
                     for_training=self.for_training,
                     inputs_need_grad=self._inputs_need_grad,
                     grad_req=self._grad_req)
            if self.params_initialized:
                arg, aux = self.get_params()
                mod.init_params(initializer=self._initializer,
                                arg_params=arg, aux_params=aux,
                                allow_missing=False, force_init=True)
                if self._curr_module.optimizer_initialized:
                    mod._optimizer = self._curr_module._optimizer
                    mod._updater_states = self._curr_module._updater_states
                    mod.optimizer_initialized = True
            self._buckets[bucket_key] = mod
        else:
            mod = self._buckets[bucket_key]
            if self.params_initialized:
                # pull current params from the previously-active bucket
                arg, aux = self._curr_module.get_params()
                mod.set_params(arg, aux)
                mod._updater_states = self._curr_module._updater_states
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    # ------------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        self._initializer = initializer
        self._curr_module.init_params(
            initializer=initializer, arg_params=arg_params,
            aux_params=aux_params, allow_missing=allow_missing,
            force_init=force_init, allow_extra=allow_extra)
        self.params_initialized = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._curr_module.init_optimizer(kvstore, optimizer,
                                         optimizer_params,
                                         force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._curr_module:
                mod._optimizer = self._curr_module._optimizer
                mod._updater_states = self._curr_module._updater_states
                mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def get_params(self):
        return self._curr_module.get_params()

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        key = getattr(data_batch, "bucket_key", None)
        if key is None:
            key = self._curr_bucket_key
        self.switch_bucket(key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()
        # states dict is shared by reference; params live per-module, so
        # propagate lazily on the next switch (see switch_bucket)

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)
