"""BaseModule: the symbol-era training API skeleton (reference:
python/mxnet/module/base_module.py — fit/score/predict drive the
bind → init_params → init_optimizer → forward_backward → update loop)."""
from __future__ import annotations

import logging
import time
from typing import List, Optional

from ..base import MXNetError
from .. import metric as metric_mod
from ..callback import BatchEndParam

__all__ = ["BaseModule"]


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # ------------------------------------------------------------------
    # subclass interface
    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write"):
        raise NotImplementedError

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False,
                    allow_extra=False):
        raise NotImplementedError

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    # ------------------------------------------------------------------
    # generic drivers
    # ------------------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None,
              reset=True, epoch=0):
        if not self.binded or not self.params_initialized:
            raise MXNetError("score: call bind() and init_params() first")
        eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            for cb in _as_list(batch_end_callback):
                cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                 eval_metric=eval_metric, locals=locals()))
        for cb in _as_list(score_end_callback):
            cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                             eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        from .. import ndarray as nd
        if not self.binded or not self.params_initialized:
            raise MXNetError("predict: call bind() and init_params() first")
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [o[0:o.shape[0] - pad].copy()
                    for o in self.get_outputs()]
            output_list.append(outs)
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.concatenate([b[i] for b in output_list], axis=0)
                      for i in range(num_outputs)]
            return merged[0] if num_outputs == 1 else merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """The canonical training loop (reference: BaseModule.fit)."""
        if num_epoch is None:
            raise MXNetError("fit: num_epoch is required")
        if initializer is None:
            from .. import initializer as init_mod
            initializer = init_mod.Uniform(0.01)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        if validation_metric is None:
            validation_metric = eval_metric
        eval_metric = metric_mod.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                if monitor is not None:
                    monitor.toc_print()
                self.update_metric(eval_metric, data_batch.label)
                for cb in _as_list(batch_end_callback):
                    cb(BatchEndParam(epoch=epoch, nbatch=nbatch,
                                     eval_metric=eval_metric,
                                     locals=locals()))
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)
            arg_params, aux_params = self.get_params()
            for cb in _as_list(epoch_end_callback):
                cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    # ------------------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol
