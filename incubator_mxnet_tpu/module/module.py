"""Module: executor-backed symbolic training (reference:
python/mxnet/module/module.py).

TPU-native notes: the reference's Module owns a DataParallelExecutorGroup
slicing each batch over a ctx list; here one jit-compiled Executor runs the
program and multi-device data parallelism is the SPMD path
(``parallel.SPMDTrainer``) rather than per-device executor replicas — the
API surface (bind/init_params/init_optimizer/forward/backward/update) is
preserved."""
from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context
from .. import ndarray as nd
from .. import optimizer as opt_mod
from ..initializer import InitDesc
from ..io import DataDesc
from ..model import save_checkpoint as _save_checkpoint, \
    load_checkpoint as _load_checkpoint
from ..ndarray.ndarray import NDArray
from .base_module import BaseModule

__all__ = ["Module"]


def _canon_shapes(shapes) -> List[DataDesc]:
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            name, shape = s[0], s[1]
            dtype = s[2] if len(s) > 2 else _np.float32
            out.append(DataDesc(name, shape, dtype))
    return out


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if isinstance(context, (list, tuple)):
            if len(context) > 1:
                logger.warning(
                    "Module: multi-context DP is the SPMD path on TPU; "
                    "using the first context (use parallel.SPMDTrainer "
                    "for multi-chip)")
            context = context[0] if context else None
        self._context = context if context is not None else current_context()
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = set(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater_states: Dict[int, object] = {}
        self._data_shapes: List[DataDesc] = []
        self._label_shapes: List[DataDesc] = []
        self._grad_req = "write"

    # ------------------------------------------------------------------
    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = _load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded_params = (arg_params, aux_params)
        return mod

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg_params, aux_params = self.get_params()
        _save_checkpoint(prefix, epoch, self._symbol, arg_params,
                         aux_params)
        if save_optimizer_states:
            self.save_optimizer_states(f"{prefix}-{epoch:04d}.states")

    # ------------------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        if not self.binded:
            raise MXNetError("output_shapes: not bound")
        return list(zip(self.output_names,
                        [o.shape for o in self._exec.outputs])) \
            if self._exec.outputs else []

    # ------------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, grad_req="write",
             shared_module=None):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self._data_shapes = _canon_shapes(data_shapes)
        self._label_shapes = _canon_shapes(label_shapes)
        self.for_training = for_training
        self._inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req

        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        shape_kwargs.update({d.name: d.shape for d in self._label_shapes})
        type_dict = {d.name: d.dtype for d in
                     self._data_shapes + self._label_shapes}
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._param_names and for_training \
                    and n not in self._fixed_param_names:
                req[n] = grad_req
            elif n in self._data_names and inputs_need_grad:
                req[n] = "write"
            else:
                req[n] = "null"
        from ..executor import Executor
        self._exec = Executor.simple_bind(
            self._symbol, self._context, grad_req=req,
            type_dict=type_dict, **shape_kwargs)
        self.binded = True
        if getattr(self, "_preloaded_params", None) is not None:
            arg_params, aux_params = self._preloaded_params
            self.set_params(arg_params, aux_params)
            self._preloaded_params = None

    # ------------------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("init_params: call bind first")
        attr_dict = self._symbol.attr_dict()
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arr._data = self._as_jax(arg_params[name], arr)
            elif initializer is not None:
                desc = InitDesc(name, attr_dict.get(name, {}))
                initializer(desc, arr)
            elif not allow_missing:
                raise MXNetError(f"init_params: no value for '{name}' and "
                                 "no initializer")
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                arr._data = self._as_jax(aux_params[name], arr)
            elif initializer is not None:
                desc = InitDesc(name, attr_dict.get(name, {}))
                initializer(desc, arr)
        if arg_params:
            extra = [k for k in arg_params if k not in self._param_names
                     and k not in self._data_names
                     and k not in self._label_names]
            if extra and not allow_extra:
                raise MXNetError(f"init_params: extra arg_params {extra}")
        self.params_initialized = True

    def _as_jax(self, v, like: NDArray):
        v = v if isinstance(v, NDArray) else nd.array(v, ctx=self._context)
        if tuple(v.shape) != tuple(like.shape):
            raise MXNetError(
                f"param shape mismatch: got {v.shape}, expected "
                f"{like.shape}")
        return v._data.astype(like.dtype)

    def get_params(self) -> Tuple[Dict, Dict]:
        if not self.binded:
            raise MXNetError("get_params: not bound")
        arg = {n: self._exec.arg_dict[n].copy() for n in self._param_names}
        aux = {n: self._exec.aux_dict[n].copy() for n in self._aux_names}
        return arg, aux

    # ------------------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, opt_mod.Optimizer):
            self._optimizer = optimizer
        else:
            params = dict(optimizer_params) \
                if not isinstance(optimizer_params, dict) \
                else dict(optimizer_params)
            self._optimizer = opt_mod.create(optimizer, **params)
        self._updater_states = {}
        self.optimizer_initialized = True

    # ------------------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if not self.binded or not self.params_initialized:
            raise MXNetError("forward: bind and init_params first")
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data or []):
            feeds[name] = arr
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                feeds[name] = arr
        n_real = None
        if not is_train:
            feeds, n_real = self._pad_feeds(feeds)
        self._exec.forward(is_train=is_train, **feeds)
        if n_real is not None:
            full = self._data_shapes[0].shape[0]
            self._exec.outputs = [
                o[0:n_real] if o.shape and o.shape[0] == full else o
                for o in self._exec.outputs]

    def _pad_feeds(self, feeds):
        """Inference-time shape bucketing: a short last batch is
        zero-padded up to the BOUND batch size so it dispatches the
        already-compiled program instead of tracing a fresh one per
        leftover size; ``forward`` slices the outputs back to the true
        row count.  Only fires when every fed array differs from its
        bound shape solely by a smaller leading dim (per-example
        inference semantics — padding rows cannot perturb real rows with
        ``is_train=False``)."""
        bound = {d.name: tuple(d.shape)
                 for d in self._data_shapes + self._label_shapes}
        n = pad_to = None
        for name, arr in feeds.items():
            want = bound.get(name)
            if want is None or tuple(arr.shape) == want:
                continue
            if (len(arr.shape) != len(want)
                    or tuple(arr.shape[1:]) != want[1:]
                    or arr.shape[0] >= want[0]
                    or (n is not None and arr.shape[0] != n)):
                return feeds, None      # not a pure short-batch case
            n, pad_to = int(arr.shape[0]), int(want[0])
        if n is None:
            return feeds, None
        padded = {}
        for name, arr in feeds.items():
            want = bound[name]
            if tuple(arr.shape) == want:
                padded[name] = arr
                continue
            arr = arr if isinstance(arr, NDArray) \
                else nd.array(arr, ctx=self._context)
            filler = nd.zeros((pad_to - n,) + want[1:], ctx=self._context,
                              dtype=arr.dtype)
            padded[name] = nd.concatenate([arr, filler], axis=0)
        return padded, n

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Apply one optimizer step per parameter (reference: Module.update
        → kvstore push/pull or Updater; 'local' kvstore on one chip is a
        direct update — the multi-chip gradient mean is the SPMD psum
        path)."""
        if not self.optimizer_initialized:
            raise MXNetError("update: call init_optimizer first")
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            if i not in self._updater_states:
                self._updater_states[i] = \
                    self._optimizer.create_state(i, weight)
            self._optimizer.update(i, weight, grad,
                                   self._updater_states[i])

    def install_monitor(self, mon):
        """Attach a Monitor to this module's executor (reference:
        Module.install_monitor — which likewise requires bind first)."""
        if not self.binded or self._exec is None:
            raise MXNetError("install_monitor: bind() the module first")
        mon.install(self._exec)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        if not getattr(self, "_inputs_need_grad", False):
            raise MXNetError("bind with inputs_need_grad=True first")
        return [self._exec.grad_dict[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self.get_outputs())

    # ------------------------------------------------------------------
    def save_optimizer_states(self, fname):
        import pickle
        states = {i: (None if s is None else
                      _state_to_numpy(s))
                  for i, s in self._updater_states.items()}
        with open(fname, "wb") as f:
            pickle.dump(states, f)

    def load_optimizer_states(self, fname):
        import pickle
        with open(fname, "rb") as f:
            states = pickle.load(f)
        self._updater_states = {
            i: (None if s is None else _state_from_numpy(s, self._context))
            for i, s in states.items()}


def _state_to_numpy(state):
    if isinstance(state, (list, tuple)):
        return type(state)(_state_to_numpy(s) for s in state)
    if isinstance(state, NDArray):
        return state.asnumpy()
    return state


def _state_from_numpy(state, ctx):
    if isinstance(state, (list, tuple)):
        return type(state)(_state_from_numpy(s, ctx) for s in state)
    if isinstance(state, _np.ndarray):
        return nd.array(state, ctx=ctx)
    return state
