"""In-program health plane: device-side training/decode statistics with
zero host syncs on the hot path.

Whole-step capture (fused optimizer, SPMD step, CompiledLoop chunks)
amortized the host out of the training loop — and blinded it: when a
chunk skips a non-finite step today the host learns only a COUNT, not
which parameter leaf went non-finite, what the grad norms looked like,
or when the loss started drifting.  This module computes that evidence
INSIDE the donated programs and surfaces it asynchronously:

* :func:`train_step_health` — per-leaf gradient L2 norms, a per-leaf
  finite mask, update/weight ratios and the loss / global-grad-norm
  scalars, traced as pure EXTRA outputs of the step/chunk program.  The
  inputs are firewalled behind ``jax.lax.optimization_barrier`` so the
  stats cannot fuse into (and re-associate) the update arithmetic —
  enabling the plane is bit-exact on params (the zero1 all-gather
  precedent; asserted by tests/test_health.py).
* :class:`HealthMonitor` — the host-side companion: device stat trees
  queue per dispatch and drain only when already finished
  (``is_ready()``, the ``CompiledLoop._drain_skipped`` pattern) or at
  explicit sync points, so the mxtpu-lint host-sync checker stays
  clean.  Drained records fold into :data:`telemetry.health_ring` (the
  bounded StepHealth ring) and feed the anomaly detector.
* the anomaly detector — loss spike vs a rolling window
  (``MXNET_HEALTH_SPIKE_FACTOR`` x window mean), grad-norm explosion
  (``MXNET_HEALTH_GRADNORM_FACTOR``), and first-nonfinite-leaf
  attribution by tree path.  Every anomaly publishes the ``HEALTH``
  topic, bumps ``mxtpu_health_anomalies`` and fires a debounced FAULT
  ``event="anomaly"`` — which the flight recorder maps to a
  ``training_anomaly`` dump whose payload (the ``health`` provider
  below) names the exact offending leaf, the step, the last-k
  StepHealth records, and the dispatch-ledger context.
* :func:`decode_health` — the serving twin: per-decode-step logit max /
  entropy / finite-check ride the decode outputs
  (``serving/engine.py``); the continuous batcher turns a non-finite
  row into a ``nonfinite_generation`` anomaly naming the implicated
  request ids.

Everything is gated by ``MXNET_HEALTH_PLANE`` (default off): with the
plane off the compiled programs are byte-identical to before this
module existed.  Knobs (docs/env_var.md): ``MXNET_HEALTH_PLANE``,
``MXNET_HEALTH_RING``, ``MXNET_HEALTH_WINDOW``,
``MXNET_HEALTH_SPIKE_FACTOR``, ``MXNET_HEALTH_GRADNORM_FACTOR``.
"""
from __future__ import annotations

import threading
import time as _time
import weakref as _weakref
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as _np

from .base import getenv_bool, getenv_float, getenv_int
from . import telemetry as _telemetry

__all__ = ["enabled", "train_step_health", "decode_health",
           "HealthMonitor", "serving_anomaly", "sync", "last_anomaly",
           "report", "reset"]

#: anomalies of one kind re-fire the FAULT dump trigger at most once per
#: this many seconds (a NaN plateau flags every step; one incident, one
#: artifact) — the flight recorder debounces per-reason on top
_FAULT_DEBOUNCE_S = 5.0

#: spike detection needs this many finite in-window samples first
_MIN_WINDOW = 8


def enabled() -> bool:
    """``MXNET_HEALTH_PLANE``: trace health stats into the compiled
    step/chunk/decode programs (default off — programs unchanged)."""
    return getenv_bool("MXNET_HEALTH_PLANE", False)


def window_size() -> int:
    """``MXNET_HEALTH_WINDOW``: rolling-window length (steps) for the
    loss-spike / grad-explosion baselines."""
    return max(2, getenv_int("MXNET_HEALTH_WINDOW", 32))


def spike_factor() -> float:
    """``MXNET_HEALTH_SPIKE_FACTOR``: loss > factor x window mean flags
    a ``loss_spike`` anomaly."""
    return getenv_float("MXNET_HEALTH_SPIKE_FACTOR", 4.0)


def gradnorm_factor() -> float:
    """``MXNET_HEALTH_GRADNORM_FACTOR``: global grad norm > factor x
    window mean flags a ``grad_norm_explosion`` anomaly."""
    return getenv_float("MXNET_HEALTH_GRADNORM_FACTOR", 10.0)


# ---------------------------------------------------------------------------
# In-program stat computation (traced; pure extra outputs)
# ---------------------------------------------------------------------------
def train_step_health(grads: Sequence, weights: Sequence,
                      new_weights: Sequence, loss=None) -> Dict[str, object]:
    """Trace per-leaf health stats over aligned leaf lists.

    Returns a dict of device arrays (all f32/bool, so pulling them
    never perturbs or retains the training dtypes):

    * ``grad_norms``   (n,) per-leaf L2 norm of the raw gradient
    * ``finite``       (n,) per-leaf all-finite mask
    * ``update_ratios``(n,) ||w' - w|| / (||w|| + eps) — 0 on a
      guard-skipped step, the update signature of a frozen leaf
    * ``grad_norm``    ()  global L2 norm
    * ``loss``         ()  (only when ``loss`` is given)

    Inputs pass through ``optimization_barrier`` first: the barrier
    keeps this reduction tree OUT of the update arithmetic's fusion
    clusters, so XLA cannot re-contract the update's multiply-add
    chains around it — enabling the plane stays bit-exact on params.
    """
    import jax
    import jax.numpy as jnp
    f32 = jnp.float32
    gs = [jax.lax.optimization_barrier(g) for g in grads]
    ws = [jax.lax.optimization_barrier(w) for w in weights]
    nws = [jax.lax.optimization_barrier(w) for w in new_weights]
    gsq = [jnp.sum(jnp.square(g.astype(f32))) for g in gs]
    eps = jnp.asarray(1e-12, f32)
    ratios = []
    for w, nw in zip(ws, nws):
        w32 = w.astype(f32)
        d = nw.astype(f32) - w32
        ratios.append(jnp.sqrt(jnp.sum(jnp.square(d)))
                      / (jnp.sqrt(jnp.sum(jnp.square(w32))) + eps))
    norms = jnp.sqrt(jnp.stack(gsq))
    out = {
        "grad_norms": norms,
        # derived from the sum of squares instead of a dedicated
        # isfinite pass over every leaf: NaN/Inf propagate through the
        # reduction, so a leaf is flagged iff its norm is non-finite
        # (grads large enough to overflow the f32 square ARE the
        # explosion this mask exists to catch)
        "finite": jnp.isfinite(norms),
        "update_ratios": jnp.stack(ratios),
        "grad_norm": jnp.sqrt(jnp.sum(jnp.stack(gsq))),
    }
    if loss is not None:
        out["loss"] = jax.lax.optimization_barrier(loss).astype(f32)
    return out


def decode_health(logits):
    """Trace per-slot decode health from last-position logits (S, V):
    returns ``(logit_max (S,), entropy (S,) nats, finite (S,))``.  Same
    barrier firewall as :func:`train_step_health` — the decode argmax
    stays bit-identical with the plane on."""
    import jax
    import jax.numpy as jnp
    lg = jax.lax.optimization_barrier(logits).astype(jnp.float32)
    m = jnp.max(lg, axis=-1)
    z = lg - m[..., None]
    e = jnp.exp(z)
    s = jnp.sum(e, axis=-1)
    ent = jnp.log(s) - jnp.sum(e * z, axis=-1) / s
    fin = jnp.all(jnp.isfinite(lg), axis=-1)
    return m, ent, fin


# ---------------------------------------------------------------------------
# Host-side monitor: async drain + anomaly detection
# ---------------------------------------------------------------------------
_lock = threading.Lock()
_monitors: List["_weakref.ref"] = []
_last_anomaly: Optional[dict] = None
_serving_fault: Dict[str, float] = {}


def _register(mon: "HealthMonitor") -> None:
    with _lock:
        _monitors[:] = [r for r in _monitors if r() is not None]
        _monitors.append(_weakref.ref(mon))


def sync() -> None:
    """Block until every live monitor's pending device stats are drained
    — records exact in the ring, detector caught up.  Call at
    checkpoint/eval boundaries; the training loop never needs to."""
    with _lock:
        refs = list(_monitors)
    for r in refs:
        mon = r()
        if mon is not None:
            mon.sync()


def last_anomaly() -> Optional[dict]:
    """The most recent anomaly (any monitor), or None."""
    return _last_anomaly


def reset() -> None:
    """Forget the last anomaly and drop monitor debounce state (test
    hygiene; live monitors and the ring survive — clear the ring via
    ``telemetry.health_ring.clear()``)."""
    global _last_anomaly
    with _lock:
        refs = list(_monitors)
        _last_anomaly = None
        _serving_fault.clear()
    for r in refs:
        mon = r()
        if mon is not None:
            mon._last_fault.clear()


def serving_anomaly(model: str, step: int,
                    request_ids: Sequence[str],
                    detail: str = "") -> None:
    """Record a serving-side ``nonfinite_generation`` anomaly: a decode
    dispatch produced non-finite final-position logits for the
    implicated request ids (continuous batcher, serving/batcher.py).
    Same plumbing as the training monitors — HEALTH topic,
    ``mxtpu_health_anomalies`` and the debounced FAULT
    ``event="anomaly"`` that yields one ``training_anomaly`` flight
    dump per incident."""
    global _last_anomaly
    kind = "nonfinite_generation"
    info = {"kind": kind, "step": int(step), "src": str(model),
            "leaf": None, "request_ids": [str(r) for r in request_ids],
            "detail": detail or f"non-finite decode logits for "
                                f"{len(request_ids)} request(s)",
            "time_unix": round(_time.time(), 3)}
    with _lock:
        _last_anomaly = info
    _telemetry.counter(
        "mxtpu_health_anomalies",
        "training/decode anomalies the health plane detected, "
        "by kind").inc(kind=kind, src=str(model))
    _telemetry.HEALTH.publish(**info)
    now = _time.monotonic()
    key = f"{model}:{kind}"
    with _lock:
        if now - _serving_fault.get(key, -1e9) < _FAULT_DEBOUNCE_S:
            return
        _serving_fault[key] = now
    _telemetry.FAULT.publish(site=f"health.{model}", event="anomaly",
                             kind=kind, step=int(step),
                             request_ids=list(info["request_ids"]))


class HealthMonitor:
    """Per-trainer host companion of the in-program stats.

    ``submit(step0, k, stats)`` queues one dispatch's device stat tree
    (``k`` inner steps starting at ``step0 + 1``) and opportunistically
    drains whatever already finished — ``is_ready()`` only, never a
    blocking pull, so submitting from a hot path costs a list append.
    ``sync()`` blocks (boundary use).  Folding a record updates the
    StepHealth ring, the ``mxtpu_health_*`` series and the anomaly
    detector."""

    def __init__(self, leaf_names: Sequence[str], src: str = "trainer"):
        self.names = [str(n) for n in leaf_names]
        self.src = str(src)
        self._pending: List[tuple] = []
        n = window_size()
        self._loss_win: deque = deque(maxlen=n)
        self._gnorm_win: deque = deque(maxlen=n)
        self._last_fault: Dict[str, float] = {}
        _register(self)

    # -- drain ----------------------------------------------------------
    def submit(self, step0: int, k: int, stats: Dict[str, object]) -> None:
        self._pending.append((int(step0), int(k), stats))
        self.drain(block=False)

    def drain(self, block: bool = False) -> None:
        rest = []
        for step0, k, stats in self._pending:
            probe = stats["grad_norms"]
            ready = block or not hasattr(probe, "is_ready") \
                or probe.is_ready()
            if ready:
                self._fold(step0, k, stats)
            else:
                rest.append((step0, k, stats))
        self._pending = rest

    def sync(self) -> None:
        self.drain(block=True)

    # -- folding + detection (boundary time, off the hot path) ----------
    def _fold(self, step0: int, k: int, stats: Dict[str, object]) -> None:
        host = {kk: _np.asarray(v) for kk, v in stats.items()}
        n = len(self.names)
        gns = host["grad_norms"].reshape(k, n)
        fins = host["finite"].reshape(k, n)
        upds = host["update_ratios"].reshape(k, n)
        gnorm = host["grad_norm"].reshape(k)
        loss = host["loss"].reshape(k) if "loss" in host else None
        for i in range(k):
            step = step0 + 1 + i
            fin_row = fins[i]
            all_fin = bool(fin_row.all())
            rec = {
                "step": step,
                "src": self.src,
                "loss": float(loss[i]) if loss is not None else None,
                "grad_norm": float(gnorm[i]),
                "max_update_ratio": float(upds[i].max()) if n else 0.0,
                "finite": all_fin,
            }
            if not all_fin:
                bad = int(_np.argmin(fin_row))
                rec["nonfinite_leaf"] = self.names[bad]
            _telemetry.health_ring.record(rec)
            self._publish_metrics(rec)
            self._detect(rec)

    def _publish_metrics(self, rec: dict) -> None:
        _telemetry.counter(
            "mxtpu_health_steps",
            "train steps folded into the StepHealth ring "
            "(health plane on)").inc(src=self.src)
        _telemetry.gauge(
            "mxtpu_health_grad_norm",
            "global gradient L2 norm of the most recent drained "
            "step").set(rec["grad_norm"], src=self.src)
        _telemetry.gauge(
            "mxtpu_health_update_ratio_max",
            "largest per-leaf ||dw||/||w|| of the most recent drained "
            "step (0 = guard-skipped or frozen)").set(
            rec["max_update_ratio"], src=self.src)
        if rec["loss"] is not None:
            _telemetry.gauge(
                "mxtpu_health_loss",
                "training loss of the most recent drained step").set(
                rec["loss"], src=self.src)

    def _detect(self, rec: dict) -> None:
        step = rec["step"]
        if not rec["finite"]:
            leaf = rec.get("nonfinite_leaf")
            self._anomaly("nonfinite", step, rec, leaf=leaf,
                          detail=f"first non-finite gradient leaf "
                                 f"{leaf!r} at step {step}")
            # a non-finite step must not poison the rolling baselines
            return
        loss, gnorm = rec["loss"], rec["grad_norm"]
        if loss is not None and _np.isfinite(loss) \
                and len(self._loss_win) >= _MIN_WINDOW:
            mean = sum(self._loss_win) / len(self._loss_win)
            if mean > 0 and loss > spike_factor() * mean:
                self._anomaly(
                    "loss_spike", step, rec,
                    detail=f"loss {loss:.4g} > {spike_factor():g}x "
                           f"rolling mean {mean:.4g}")
        if _np.isfinite(gnorm) and len(self._gnorm_win) >= _MIN_WINDOW:
            mean = sum(self._gnorm_win) / len(self._gnorm_win)
            if mean > 0 and gnorm > gradnorm_factor() * mean:
                self._anomaly(
                    "grad_norm_explosion", step, rec,
                    detail=f"grad norm {gnorm:.4g} > "
                           f"{gradnorm_factor():g}x rolling mean "
                           f"{mean:.4g}")
        if loss is not None and _np.isfinite(loss):
            self._loss_win.append(float(loss))
        if _np.isfinite(gnorm):
            self._gnorm_win.append(float(gnorm))

    def _anomaly(self, kind: str, step: int, rec: dict,
                 leaf: Optional[str] = None, detail: str = "") -> None:
        global _last_anomaly
        info = {"kind": kind, "step": step, "src": self.src,
                "leaf": leaf, "detail": detail, "record": dict(rec),
                "time_unix": round(_time.time(), 3)}
        with _lock:
            _last_anomaly = info
        _telemetry.counter(
            "mxtpu_health_anomalies",
            "training/decode anomalies the health plane detected, "
            "by kind").inc(kind=kind, src=self.src)
        _telemetry.HEALTH.publish(**info)
        now = _time.monotonic()
        if now - self._last_fault.get(kind, -1e9) < _FAULT_DEBOUNCE_S:
            return
        self._last_fault[kind] = now
        # the flight recorder maps event="anomaly" to one debounced
        # training_anomaly dump; its "health" provider (below) carries
        # the leaf/step attribution and the ring tail
        _telemetry.FAULT.publish(site=f"health.{self.src}",
                                 event="anomaly", kind=kind, step=step,
                                 leaf=leaf)


# ---------------------------------------------------------------------------
# Reporting (GET /health, mxtpu-stats --health, flight dumps)
# ---------------------------------------------------------------------------
def report(last: int = 16) -> dict:
    """JSON-ready health summary: detector status, anomaly counts, the
    last anomaly and the StepHealth ring tail."""
    anom = _telemetry.counter(
        "mxtpu_health_anomalies",
        "training/decode anomalies the health plane detected, "
        "by kind").sample()
    if isinstance(anom, dict):
        total = float(anom.get("total", 0.0))
        by = dict(anom.get("by", {}))
    else:
        total = float(anom)
        by = {}
    return {
        "enabled": enabled(),
        "status": "anomalous" if total else "ok",
        "anomaly_total": total,
        "anomalies": by,
        "last_anomaly": _last_anomaly,
        "ring": _telemetry.health_ring.entries(last=last),
        "ring_depth": len(_telemetry.health_ring),
    }


def _flight_provider() -> dict:
    """The ``health`` section of every flight dump: for a
    ``training_anomaly`` artifact this is the forensics — the offending
    leaf and step, the last-k StepHealth records and the dispatch-ledger
    context of the programs that produced them."""
    return {
        "last_anomaly": _last_anomaly,
        "ring": _telemetry.health_ring.entries(last=32),
        "dispatch_ledger": _telemetry.dispatch_ledger(),
    }


from . import telemetry_ring as _ring  # noqa: E402  (no cycle: ring
#                                         imports telemetry only)
_ring.recorder.register_provider("health", _flight_provider)
