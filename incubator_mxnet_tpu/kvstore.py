"""KVStore: the key→value synchronization API (reference:
python/mxnet/kvstore.py; src/kvstore/kvstore_local.h, kvstore_dist.h).

TPU-native re-design (SURVEY §5.8): the reference's 'local'/'device'/'nccl'
stores aggregate per-device gradient copies; here a Parameter is ONE logical
(possibly mesh-sharded) array.  Aggregation semantics by type:

* 'local'/'device'/'nccl': values pushed for one key are summed.  Under an
  ambient ``parallel.mesh_scope`` a multi-value push lowers to ONE compiled
  XLA all-reduce over the mesh devices (the ICI path — replaces the
  reference's comm.h reduce / kvstore_nccl.h allreduce) instead of a chain
  of device-to-device adds.
* 'dist_sync'/'dist'/'tpu': additionally, every push is summed ACROSS
  PROCESSES over DCN (jax.distributed must be initialized; reference analog:
  ps-lite worker→server push + aggregate, kvstore_dist_server.h).  With one
  process this is the identity, so single-host code runs unchanged.
* 'dist_async' is refused by design: an asynchronous parameter server
  contradicts SPMD compiled execution.

2-bit gradient compression (reference: src/kvstore/gradient_compression.cc)
is implemented for dist-type stores: sign-threshold quantization with a
per-key error-feedback residual, applied to the local value before the
cross-process sum.
"""
from __future__ import annotations

import pickle
import time as _time
from typing import Dict, List, Optional

from .base import MXNetError
from . import fault as _fault
from . import telemetry as _telemetry
from .ndarray.ndarray import NDArray
from .ndarray import ndarray as _ndmod

__all__ = ["KVStore", "create"]


def _nd_nbytes(v) -> int:
    """Logical payload size of an NDArray-ish value (shape x itemsize)."""
    try:
        import numpy as _np
        n = 1
        for d in v.shape:
            n *= int(d)
        return n * _np.dtype(v.dtype).itemsize
    except Exception:
        return 0

_mesh_sum_cache: Dict = {}   # device-id tuple -> jitted replicated sum

_SINGLE_TYPES = ("local", "local_allreduce_cpu", "local_allreduce_device",
                 "device", "nccl")
_DIST_TYPES = ("dist_sync", "dist_device_sync", "dist_sync_device", "dist",
               "tpu")


def create(name="local") -> "KVStore":
    """reference: mx.kv.create."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in _SINGLE_TYPES or name in _DIST_TYPES:
        return KVStore(name)
    if "async" in name:
        raise MXNetError(
            "dist_async is unsupported by design on TPU: asynchronous "
            "parameter-server updates contradict SPMD compiled execution. "
            "Use 'dist_sync' (allreduce compiled into the step) instead.")
    raise MXNetError(f"unknown KVStore type {name!r}")


class KVStore:
    """Key→NDArray store with push/pull aggregation semantics matching the
    reference (values pushed from multiple devices are summed; pull fans the
    aggregate back out; dist types also sum across processes)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._residuals: Dict = {}   # per-key error feedback (2bit)
        if kv_type in _DIST_TYPES:
            # multi-host sync via jax.distributed (one process per host);
            # push aggregates across processes (see _cross_process_sum)
            import jax
            self._rank = jax.process_index()
            self._num_workers = jax.process_count()
        else:
            self._rank = 0
            self._num_workers = 1

    # ------------------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def _is_dist(self) -> bool:
        return self._type in _DIST_TYPES

    # ------------------------------------------------------------------
    def _norm_keys(self, key, value):
        single = not isinstance(key, (list, tuple))
        if single:
            key, value = [key], [value]
        return single, list(key), list(value)

    def init(self, key, value):
        """reference: KVStore.init — one-time value registration.  For dist
        types every process adopts rank 0's value, matching the reference's
        worker-0-init-push / everyone-pulls flow (kvstore_dist.h InitImpl)."""
        _, keys, values = self._norm_keys(key, value)
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                v = v[0]
            v = v.copy() if isinstance(v, NDArray) else _ndmod.array(v)
            if self._is_dist() and self._num_workers > 1:
                v = self._bcast_from_rank0(v)
            self._store[k] = v
            self._residuals.pop(k, None)  # fresh key: no stale feedback

    @staticmethod
    def _bcast_from_rank0(value: NDArray) -> NDArray:
        """All processes adopt rank 0's value (DCN broadcast)."""
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(value, BaseSparseNDArray):
            value = value.tostype("default")
        from jax.experimental import multihost_utils
        gathered = multihost_utils.process_allgather(value._data)
        return NDArray(gathered[0], ctx=value.ctx)

    # ------------------------------------------------------------------
    # aggregation machinery
    # ------------------------------------------------------------------
    def _aggregate(self, vlist) -> NDArray:
        """Sum values pushed for one key (reference: comm.h Reduce).  Under
        an ambient mesh, a multi-value push compiles to one XLA all-reduce
        over the mesh devices instead of a serial add chain."""
        if isinstance(vlist, NDArray):
            return vlist
        if len(vlist) == 1:
            return vlist[0]
        from .parallel import mesh as mesh_mod
        from .ndarray.sparse import BaseSparseNDArray
        mesh = mesh_mod.current_mesh()
        if (mesh is not None and mesh.devices.size >= len(vlist)
                and not any(isinstance(v, BaseSparseNDArray)
                            for v in vlist)):
            return self._mesh_reduce(vlist, mesh)
        out = vlist[0]
        for v in vlist[1:]:
            out = out + v
        return out

    @staticmethod
    def _mesh_reduce(vlist, mesh) -> NDArray:
        """One compiled all-reduce: shard the stacked values over the mesh
        devices, jit a leading-axis sum with a replicated output sharding —
        XLA lowers this to a psum over ICI (reference analogs:
        kvstore_nccl.h allreduce, comm_tree.h 2-level reduce).  The jitted
        reducer is cached per device set so the program compiles once."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        n = len(vlist)
        devs = tuple(mesh.devices.flat)[:n]
        flat_mesh = Mesh(list(devs), ("kv",))
        shape = (n,) + tuple(vlist[0].shape)
        shards = [
            jax.device_put(v._data.reshape((1,) + tuple(v.shape)), d)
            for v, d in zip(vlist, devs)
        ]
        stacked = jax.make_array_from_single_device_arrays(
            shape, NamedSharding(flat_mesh, PartitionSpec("kv")), shards)
        key = tuple(d.id for d in devs)
        fn = _mesh_sum_cache.get(key)
        if fn is None:
            import jax.numpy as jnp
            fn = _telemetry.instrument_jit(
                "kvstore",
                jax.jit(lambda x: jnp.sum(x, axis=0),
                        out_shardings=NamedSharding(flat_mesh,
                                                    PartitionSpec())))
            _mesh_sum_cache[key] = fn
        return NDArray(fn(stacked), ctx=vlist[0].ctx)

    def _cross_process_sum(self, value: NDArray) -> NDArray:
        """Sum a per-process value over all processes (the DCN path;
        reference analog: ps-lite push → server aggregate → pull,
        kvstore_dist_server.h DataHandleEx).  Identity for one process."""
        if self._num_workers == 1:
            return value
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(value, BaseSparseNDArray):
            value = value.tostype("default")
        from jax.experimental import multihost_utils
        summed = multihost_utils.process_allgather(value._data).sum(axis=0)
        return NDArray(summed, ctx=value.ctx)

    def _compress(self, k, value: NDArray) -> NDArray:
        """2-bit sign-threshold quantization with error feedback
        (reference: gradient_compression.cc GradientCompression::Quantize).
        Values become {-t, 0, +t}; the quantization error is carried to the
        next push.  Sparse values pass through uncompressed (the reference
        compresses dense keys only)."""
        import jax.numpy as jnp
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(value, BaseSparseNDArray):
            return value
        t = float(self._compression_params.get("threshold", 0.5))
        res = self._residuals.get(k)
        g = value._data if res is None else value._data + res
        q = jnp.where(g >= t, t, jnp.where(g <= -t, -t, 0.0)).astype(g.dtype)
        self._residuals[k] = g - q
        return NDArray(q, ctx=value.ctx)

    # ------------------------------------------------------------------
    def _push_one(self, k, agg):
        """The retried unit of push: transport + store mutation for one
        key.  The fault site fires FIRST, before any mutation, so a
        retried attempt replays an idempotent computation (compression's
        error-feedback residual is updated by the caller, outside the
        retry, exactly once per push)."""
        _fault.inject("kvstore.push")
        if self._is_dist():
            agg = self._cross_process_sum(agg)
        if self._updater is not None:
            self._updater(_key_int(k), agg, self._store[k])
        else:
            self._store[k] = agg.copy()

    def push(self, key, value, priority=0):
        """Push value(s); multiple values per key are summed; dist types
        also sum across processes.  With an updater set, the update is
        applied here — the 'update_on_kvstore' path.  Transport faults
        (OSError/TimeoutError — DCN hiccups, injected IOErrors) are
        absorbed by jittered-backoff retries; MXNetError (bad key, bad
        usage) is never retried."""
        observe = bool(_telemetry.KVSTORE.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        nbytes = 0
        with _telemetry.trace_span("kvstore.push", cat="kvstore"):
            _, keys, values = self._norm_keys(key, value)
            for k, v in zip(keys, values):
                agg = self._aggregate(v)
                if k not in self._store:
                    raise MXNetError(f"key {k!r} was not init()-ed")
                if observe:
                    nbytes += _nd_nbytes(agg)
                if self._is_dist() and self._compression_params and \
                        self._compression_params.get("type") == "2bit":
                    agg = self._compress(k, agg)
                _fault.retry_call(self._push_one, k, agg,
                                  site="kvstore.push")
        if observe:
            _telemetry.KVSTORE.publish(
                op="push", nbytes=nbytes,
                seconds=_time.perf_counter() - t0)

    def _pull_one(self, src, targets):
        """The retried unit of pull: transport + target copies for one
        key.  Copies overwrite the targets wholesale, so a replay after
        a mid-copy fault converges to the same picture."""
        _fault.inject("kvstore.pull")
        from .ndarray import sparse as _sp
        for t in targets:
            if isinstance(t, _sp.BaseSparseNDArray):
                t._replace_with(src if src.stype == t.stype
                                else src.tostype(t.stype))
            elif isinstance(src, _sp.BaseSparseNDArray):
                src.tostype("default").copyto(t)
            else:
                src.copyto(t)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        observe = bool(_telemetry.KVSTORE.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        nbytes = 0
        with _telemetry.trace_span("kvstore.pull", cat="kvstore"):
            _, keys, outs = self._norm_keys(key, out)
            for k, o in zip(keys, outs):
                if k not in self._store:
                    raise MXNetError(f"key {k!r} was not init()-ed")
                src = self._store[k]
                targets = o if isinstance(o, (list, tuple)) else [o]
                if observe:
                    nbytes += _nd_nbytes(src) * len(targets)
                _fault.retry_call(self._pull_one, src, targets,
                                  site="kvstore.pull")
        if observe:
            _telemetry.KVSTORE.publish(
                op="pull", nbytes=nbytes,
                seconds=_time.perf_counter() - t0)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: KVStorePushPullEx).  The nested
        push/pull publish their own byte counts; this event adds the
        fused-call count and end-to-end latency."""
        observe = bool(_telemetry.KVSTORE.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        with _telemetry.trace_span("kvstore.pushpull", cat="kvstore"):
            # own fault site (retry-wrapped so an injected transient is
            # absorbed here); the nested push/pull keep their own sites
            _fault.retry_call(_fault.inject, "kvstore.pushpull",
                              site="kvstore.pushpull")
            self.push(key, value, priority)
            if out is not None:
                self.pull(key, out, priority)
        if observe:
            _telemetry.KVSTORE.publish(
                op="pushpull", nbytes=0,
                seconds=_time.perf_counter() - t0)

    def pushpull_rs(self, key, value, out=None, priority=0):
        """ZeRO-1-shaped allreduce: reduce-scatter + all-gather
        (arXiv:2004.13336) instead of push-full / pull-full.

        The flattened value splits into ``num_workers`` contiguous
        slices (zero-padded to divide evenly); this worker owns the
        REDUCTION of slice ``rank`` — the reduce-scatter phase, retried
        under the ``kvstore.push`` fault site — and the owned summed
        slices are then all-gathered back into the full aggregate (the
        ``kvstore.pull`` site).  RS + AG is exactly an allreduce, so the
        result matches :meth:`pushpull` bit for bit; the SHAPE is the
        point — each replica's owned reduction is what a sharded weight
        update consumes, and once the update is sharded the gather can
        move after it (new weights instead of grads).  Single process:
        both phases are identity.  Dense values only (callers already
        gate zero1 on dense grads)."""
        import jax.numpy as jnp
        from .ndarray.sparse import BaseSparseNDArray
        observe = bool(_telemetry.KVSTORE.subscribers)
        t0 = _time.perf_counter() if observe else 0.0
        nbytes = 0
        with _telemetry.trace_span("kvstore.pushpull", cat="kvstore"):
            _fault.retry_call(_fault.inject, "kvstore.pushpull",
                              site="kvstore.pushpull")
            _, keys, values = self._norm_keys(key, value)
            _, _, outs = self._norm_keys(key, out)
            for k, v, o in zip(keys, values, outs):
                if k not in self._store:
                    raise MXNetError(f"key {k!r} was not init()-ed")
                agg = self._aggregate(v)
                if isinstance(agg, BaseSparseNDArray):
                    raise MXNetError(
                        "pushpull_rs handles dense values only")
                w = self._num_workers
                flat = agg._data.reshape(-1)
                total = int(flat.shape[0])
                shard_sz = -(-total // w)
                pad = shard_sz * w - total
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad,), flat.dtype)])

                def _rs(flat=flat, shard_sz=shard_sz, w=w):
                    # reduce-scatter: the sum of MY slice over all
                    # workers (site fires first — replays are idempotent)
                    _fault.inject("kvstore.push")
                    if w == 1:
                        return flat
                    from jax.experimental import multihost_utils
                    gathered = multihost_utils.process_allgather(
                        flat.reshape(w, shard_sz))
                    return gathered[:, self._rank, :].sum(axis=0)
                own = _fault.retry_call(_rs, site="kvstore.push")
                nbytes += int(own.size) * own.dtype.itemsize

                def _ag(own=own, w=w):
                    _fault.inject("kvstore.pull")
                    if w == 1:
                        return own
                    from jax.experimental import multihost_utils
                    return multihost_utils.process_allgather(
                        own).reshape(-1)
                full = _fault.retry_call(_ag, site="kvstore.pull")
                if pad:
                    full = full[:total]
                self._store[k] = NDArray(full.reshape(agg.shape),
                                         ctx=agg.ctx)
                if o is not None:
                    targets = o if isinstance(o, (list, tuple)) else [o]
                    _fault.retry_call(self._pull_one, self._store[k],
                                      targets, site="kvstore.pull")
        if observe:
            _telemetry.KVSTORE.publish(
                op="pushpull_rs", nbytes=nbytes,
                seconds=_time.perf_counter() - t0)

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows as row_sparse (reference:
        kvstore_local.h PullRowSparse — per-key row gather, no full-weight
        transfer)."""
        import numpy as _np
        from .ndarray import sparse as _sp
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        single, keys, outs = self._norm_keys(key, out)
        ids_list = row_ids if isinstance(row_ids, (list, tuple)) else \
            [row_ids] * len(keys)
        for k, o, ids in zip(keys, outs, ids_list):
            if k not in self._store:
                raise MXNetError(f"key {k!r} was not init()-ed")
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            rows = _np.unique(_np.asarray(
                ids.asnumpy() if isinstance(ids, NDArray) else ids
            ).astype(_np.int64).reshape(-1))
            if isinstance(src, _sp.RowSparseNDArray):
                gathered = _sp.retain(src, rows)
            else:
                import jax.numpy as jnp
                ridx = jnp.asarray(rows.astype(_np.int32))
                gathered = _sp.RowSparseNDArray(
                    src._data[ridx], ridx, src.shape, ctx=src.ctx)
            for t in targets:
                if isinstance(t, _sp.RowSparseNDArray):
                    t._replace_with(gathered)
                else:
                    gathered.tostype("default").copyto(t)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run optimizer at the store (reference: update_on_kvstore).  In
        SPMD the optimizer runs in the compiled step; this path keeps the
        API contract for Module/Trainer."""
        from . import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    @property
    def updater(self):
        return self._updater

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit compression on dist pushes (reference:
        KVStore.set_gradient_compression)."""
        params = dict(compression_params)
        if params.get("type") not in (None, "none", "2bit"):
            raise MXNetError("unknown gradient compression type")
        if params.get("type") == "2bit" and not self._is_dist():
            raise MXNetError(
                "gradient compression applies to dist KVStore types only "
                "(reference restriction)")
        self._compression_params = params
        self._residuals = {}

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on this KVStore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on this KVStore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k
