"""KVStore: the key→value synchronization API (reference:
python/mxnet/kvstore.py; src/kvstore/kvstore_local.h, kvstore_dist.h).

TPU-native re-design (SURVEY §5.8): the reference's 'local'/'device'/'nccl'
stores aggregate per-device gradient copies; here a Parameter is ONE logical
(possibly mesh-sharded) array, so single-process aggregation is summing the
pushed values.  Multi-host data parallelism rides XLA collectives compiled
into the train step (see incubator_mxnet_tpu.parallel) — 'dist_sync' maps to
a psum-over-mesh step, with KVStore retained as the API shell.  'dist_async'
is refused by design: an asynchronous parameter server contradicts SPMD
execution (documented divergence from reference kvstore_dist_server.h).
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ndarray import ndarray as _ndmod

__all__ = ["KVStore", "create"]

_SINGLE_TYPES = ("local", "local_allreduce_cpu", "local_allreduce_device",
                 "device", "nccl", "tpu")
_DIST_TYPES = ("dist_sync", "dist_device_sync", "dist_sync_device", "dist")


def create(name="local") -> "KVStore":
    """reference: mx.kv.create."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    name = name.lower()
    if name in _SINGLE_TYPES:
        return KVStore(name)
    if name in _DIST_TYPES:
        return KVStore(name)
    if "async" in name:
        raise MXNetError(
            "dist_async is unsupported by design on TPU: asynchronous "
            "parameter-server updates contradict SPMD compiled execution. "
            "Use 'dist_sync' (allreduce compiled into the step) instead.")
    raise MXNetError(f"unknown KVStore type {name!r}")


class KVStore:
    """Key→NDArray store with push/pull aggregation semantics matching the
    reference (values pushed from multiple devices are summed; pull fans the
    aggregate back out)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        if kv_type in _DIST_TYPES:
            # multi-host sync via jax.distributed (one process per host);
            # aggregation itself is compiled into the step by parallel.*
            import jax
            self._rank = jax.process_index()
            self._num_workers = jax.process_count()
        else:
            self._rank = 0
            self._num_workers = 1

    # ------------------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    # ------------------------------------------------------------------
    def _norm_keys(self, key, value):
        single = not isinstance(key, (list, tuple))
        if single:
            key, value = [key], [value]
        return single, list(key), list(value)

    def init(self, key, value):
        """reference: KVStore.init — one-time value registration."""
        _, keys, values = self._norm_keys(key, value)
        for k, v in zip(keys, values):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._store[k] = v.copy() if isinstance(v, NDArray) else \
                _ndmod.array(v)

    def _aggregate(self, vlist) -> NDArray:
        if isinstance(vlist, NDArray):
            return vlist
        if len(vlist) == 1:
            return vlist[0]
        out = vlist[0]
        for v in vlist[1:]:
            out = out + v
        return out

    def push(self, key, value, priority=0):
        """Push value(s); multiple values per key are summed (reference:
        comm.h Reduce).  With an updater set, the update is applied here —
        the 'update_on_kvstore' path."""
        _, keys, values = self._norm_keys(key, value)
        for k, v in zip(keys, values):
            agg = self._aggregate(v)
            if k not in self._store:
                raise MXNetError(f"key {k!r} was not init()-ed")
            if self._updater is not None:
                self._updater(_key_int(k), agg, self._store[k])
            else:
                self._store[k] = agg.copy()

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        _, keys, outs = self._norm_keys(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k!r} was not init()-ed")
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            from .ndarray import sparse as _sp
            for t in targets:
                if isinstance(t, _sp.BaseSparseNDArray):
                    t._replace_with(src if src.stype == t.stype
                                    else src.tostype(t.stype))
                elif isinstance(src, _sp.BaseSparseNDArray):
                    src.tostype("default").copyto(t)
                else:
                    src.copyto(t)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull (reference: KVStorePushPullEx)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows as row_sparse (reference:
        kvstore_local.h PullRowSparse — per-key row gather, no full-weight
        transfer)."""
        import numpy as _np
        from .ndarray import sparse as _sp
        if row_ids is None:
            raise MXNetError("row_sparse_pull requires row_ids")
        single, keys, outs = self._norm_keys(key, out)
        ids_list = row_ids if isinstance(row_ids, (list, tuple)) else \
            [row_ids] * len(keys)
        for k, o, ids in zip(keys, outs, ids_list):
            if k not in self._store:
                raise MXNetError(f"key {k!r} was not init()-ed")
            src = self._store[k]
            targets = o if isinstance(o, (list, tuple)) else [o]
            rows = _np.unique(_np.asarray(
                ids.asnumpy() if isinstance(ids, NDArray) else ids
            ).astype(_np.int64).reshape(-1))
            if isinstance(src, _sp.RowSparseNDArray):
                gathered = _sp.retain(src, rows)
            else:
                import jax.numpy as jnp
                ridx = jnp.asarray(rows.astype(_np.int32))
                gathered = _sp.RowSparseNDArray(
                    src._data[ridx], ridx, src.shape, ctx=src.ctx)
            for t in targets:
                if isinstance(t, _sp.RowSparseNDArray):
                    t._replace_with(gathered)
                else:
                    gathered.tostype("default").copyto(t)

    # ------------------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run optimizer at the store (reference: update_on_kvstore).  In
        SPMD the optimizer runs in the compiled step; this path keeps the
        API contract for Module/Trainer."""
        from . import optimizer as opt_mod
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)

    @property
    def updater(self):
        return self._updater

    def set_gradient_compression(self, compression_params):
        self._compression_params = dict(compression_params)
        if compression_params.get("type") not in (None, "none", "2bit"):
            raise MXNetError("unknown gradient compression type")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on this KVStore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on this KVStore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k
