"""Console entry points (reference analog: the reference ships its CLI
as ``tools/*.py`` scripts; packaging exposes them as ``im2rec`` and
``mxtpu-launch`` commands).

In a source checkout the implementations live in ``tools/`` next to the
package; when only the wheel is installed the source scripts are absent
and we fail with a clear message rather than a stack trace.
"""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_tool(name):
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "tools", f"{name}.py")
    if not os.path.exists(path):
        raise SystemExit(
            f"{name}: the '{name}' tool ships in the source tree "
            f"(tools/{name}.py) — run from a checkout of the repository")
    spec = importlib.util.spec_from_file_location(f"_tool_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def im2rec_main():
    """Pack an image list into RecordIO (tools/im2rec.py)."""
    sys.exit(_load_tool("im2rec").main())


def launch_main():
    """Spawn a multi-process training job (tools/launch.py)."""
    sys.exit(_load_tool("launch").main())


def stats_main():
    """``mxtpu-stats`` — run a script under runtime telemetry and print
    the metrics afterwards::

        mxtpu-stats [--format prometheus|json] [--out PATH]
                    [--serve [--port N]] script.py [args...]

    The script runs in-process (as ``__main__``) with the telemetry
    collector started, so every layer (op dispatch, compile cache,
    kvstore, trainer, dataloader) is observed without touching the
    script.  Metrics go to --out (or stdout) when the script finishes —
    including when it raises.  With ``--serve`` the live HTTP exporter
    runs for the duration of the script (``/metrics``, ``/healthz``,
    ``/trace`` on --port, default 9100), so a long training run can be
    scraped and its span tree inspected while it executes."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="mxtpu-stats",
        description="run a python script with MXNET_TELEMETRY collection "
                    "and print the metrics dump")
    ap.add_argument("--format", choices=("prometheus", "json"),
                    default="prometheus")
    ap.add_argument("--out", default=None,
                    help="write the dump here instead of stdout")
    ap.add_argument("--serve", action="store_true",
                    help="serve live /metrics, /healthz and /trace over "
                         "HTTP while the script runs")
    ap.add_argument("--port", type=int, default=9100,
                    help="HTTP exporter port for --serve (default 9100; "
                         "0 picks an ephemeral port)")
    ap.add_argument("script", help="python script to run")
    ap.add_argument("args", nargs=argparse.REMAINDER,
                    help="arguments passed to the script")
    ns = ap.parse_args()

    from . import telemetry
    telemetry.start()
    if ns.serve:
        from . import telemetry_http
        srv = telemetry_http.start_server(ns.port)
        sys.stderr.write(
            f"mxtpu-stats: serving /metrics /healthz /trace on "
            f"http://0.0.0.0:{srv.server_address[1]}\n")

    import runpy
    sys.argv = [ns.script] + ns.args
    status = 0
    try:
        runpy.run_path(ns.script, run_name="__main__")
    except SystemExit as e:
        status = e.code if isinstance(e.code, int) else (0 if e.code is None
                                                         else 1)
    except BaseException:
        import traceback
        traceback.print_exc()
        status = 1

    if ns.format == "prometheus":
        text = telemetry.render_prometheus()
    else:
        import json
        text = json.dumps(telemetry.snapshot(), indent=2, default=str) + "\n"
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    sys.exit(status)
