"""Console entry points (reference analog: the reference ships its CLI
as ``tools/*.py`` scripts; packaging exposes them as ``im2rec`` and
``mxtpu-launch`` commands).

In a source checkout the implementations live in ``tools/`` next to the
package; when only the wheel is installed the source scripts are absent
and we fail with a clear message rather than a stack trace.
"""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_tool(name):
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "tools", f"{name}.py")
    if not os.path.exists(path):
        raise SystemExit(
            f"{name}: the '{name}' tool ships in the source tree "
            f"(tools/{name}.py) — run from a checkout of the repository")
    spec = importlib.util.spec_from_file_location(f"_tool_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def im2rec_main():
    """Pack an image list into RecordIO (tools/im2rec.py)."""
    sys.exit(_load_tool("im2rec").main())


def launch_main():
    """Spawn a multi-process training job (tools/launch.py)."""
    sys.exit(_load_tool("launch").main())
