"""Console entry points (reference analog: the reference ships its CLI
as ``tools/*.py`` scripts; packaging exposes them as ``im2rec`` and
``mxtpu-launch`` commands).

In a source checkout the implementations live in ``tools/`` next to the
package; when only the wheel is installed the source scripts are absent
and we fail with a clear message rather than a stack trace.
"""
from __future__ import annotations

import importlib.util
import os
import sys
import time


def _load_tool(name):
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "tools", f"{name}.py")
    if not os.path.exists(path):
        raise SystemExit(
            f"{name}: the '{name}' tool ships in the source tree "
            f"(tools/{name}.py) — run from a checkout of the repository")
    spec = importlib.util.spec_from_file_location(f"_tool_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def im2rec_main():
    """Pack an image list into RecordIO (tools/im2rec.py)."""
    sys.exit(_load_tool("im2rec").main())


def launch_main():
    """Spawn a multi-process training job (tools/launch.py)."""
    sys.exit(_load_tool("launch").main())


def stats_main():
    """``mxtpu-stats`` — run a script under runtime telemetry and print
    the metrics afterwards::

        mxtpu-stats [--format prometheus|json] [--out PATH]
                    [--serve [--port N]] [--slo] [--flight-dump PATH]
                    script.py [args...]
        mxtpu-stats --fleet http://router:9000 [--slo] [--out PATH]
        mxtpu-stats --fleet URL --memory | --programs | --health |
                    --profile SECS

    With ``--fleet`` no script runs: the federated fleet view is pulled
    from a running ``mxtpu-router`` (or a single replica) instead — its
    aggregated ``/metrics`` exposition, merged ``/slo`` with ``--slo``,
    the device-memory breakdown with ``--memory``, the runtime
    program-set inventory with ``--programs``, the health-plane report
    with ``--health``, or an on-demand profiler
    capture (``POST /debug/profile``, fanned out to every replica when
    URL is a router) with ``--profile SECONDS`` — printed to stdout or
    ``--out``.

    Otherwise the script runs in-process (as ``__main__``) with the telemetry
    collector started, so every layer (op dispatch, compile cache,
    kvstore, trainer, dataloader) is observed without touching the
    script.  Metrics go to --out (or stdout) when the script finishes —
    including when it raises.  With ``--serve`` the live HTTP exporter
    runs for the duration of the script (``/metrics``, ``/healthz``,
    ``/trace`` on --port, default 9100), so a long training run can be
    scraped and its span tree inspected while it executes."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="mxtpu-stats",
        description="run a python script with MXNET_TELEMETRY collection "
                    "and print the metrics dump")
    ap.add_argument("--format", choices=("prometheus", "json"),
                    default="prometheus")
    ap.add_argument("--out", default=None,
                    help="write the dump here instead of stdout")
    ap.add_argument("--serve", action="store_true",
                    help="serve live /metrics, /healthz and /trace over "
                         "HTTP while the script runs")
    ap.add_argument("--port", type=int, default=9100,
                    help="HTTP exporter port for --serve (default 9100; "
                         "0 picks an ephemeral port)")
    ap.add_argument("--slo", action="store_true",
                    help="also print the per-model SLO state (burn "
                         "rate, error budget) after the script")
    ap.add_argument("--flight-dump", metavar="PATH", default=None,
                    help="write a flight-recorder postmortem JSON to "
                         "PATH after the script (always written, even "
                         "on success — useful for inspecting the ring)")
    ap.add_argument("--fleet", metavar="URL", default=None,
                    help="pull the federated fleet view from a running "
                         "mxtpu-router at URL instead of running a "
                         "script (aggregated /metrics, or merged /slo "
                         "with --slo)")
    ap.add_argument("--memory", action="store_true",
                    help="with --fleet: fetch the device-memory "
                         "breakdown (GET /memory — per-owner HBM "
                         "attribution) instead of /metrics")
    ap.add_argument("--programs", action="store_true",
                    help="with --fleet: fetch the runtime program-set "
                         "inventory (GET /programs — dispatch ledger + "
                         "expected-vs-compiled accounting)")
    ap.add_argument("--health", action="store_true",
                    help="with --fleet: fetch the health-plane report "
                         "(GET /health — anomaly state, StepHealth ring "
                         "tail, per-model decode stats; worst-replica "
                         "rollup when URL is a router)")
    ap.add_argument("--profile", metavar="SECONDS", type=float,
                    default=None,
                    help="with --fleet: trigger an on-demand profiler "
                         "capture (POST /debug/profile?seconds=) and "
                         "print the per-replica artifact paths")
    ap.add_argument("script", nargs="?", default=None,
                    help="python script to run")
    ap.add_argument("args", nargs=argparse.REMAINDER,
                    help="arguments passed to the script")
    ns = ap.parse_args()

    if ns.fleet:
        sys.exit(_fleet_stats(ns))
    if ns.memory or ns.programs or ns.health or ns.profile is not None:
        ap.error("--memory/--programs/--health/--profile need --fleet "
                 "URL (they query a running server)")
    if ns.script is None:
        ap.error("a script is required unless --fleet URL is given")

    from . import telemetry
    telemetry.start()
    if ns.serve:
        from . import telemetry_http
        srv = telemetry_http.start_server(ns.port)
        sys.stderr.write(
            f"mxtpu-stats: serving /metrics /healthz /trace on "
            f"http://0.0.0.0:{srv.server_address[1]}\n")

    import runpy
    sys.argv = [ns.script] + ns.args
    status = 0
    try:
        runpy.run_path(ns.script, run_name="__main__")
    except SystemExit as e:
        status = e.code if isinstance(e.code, int) else (0 if e.code is None
                                                         else 1)
    except BaseException:
        import traceback
        traceback.print_exc()
        status = 1

    if ns.format == "prometheus":
        text = telemetry.render_prometheus()
    else:
        import json
        text = json.dumps(telemetry.snapshot(), indent=2, default=str) + "\n"
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    if ns.slo:
        import json
        from . import telemetry_http
        sys.stdout.write(json.dumps(telemetry_http.slo_body(), indent=2,
                                    default=str) + "\n")
    if ns.flight_dump:
        from . import telemetry_ring
        path = telemetry_ring.recorder.dump("cli", path=ns.flight_dump)
        sys.stderr.write(f"mxtpu-stats: flight dump -> {path}\n")
    sys.exit(status)


def _fleet_stats(ns) -> int:
    """``mxtpu-stats --fleet URL``: fetch the router's federated view
    (``/metrics`` by default; ``--slo``/``--memory``/``--programs``/
    ``--health`` pick the JSON views, ``--profile SECONDS`` triggers a
    capture)."""
    from urllib.error import URLError
    from urllib.request import Request, urlopen

    base = ns.fleet.rstrip("/")
    if "://" not in base:
        base = "http://" + base
    timeout = 10.0
    req = None
    if ns.profile is not None:
        # the capture blocks server-side for the window plus profiler
        # startup and trace serialization; wait them out
        path = f"/debug/profile?seconds={ns.profile}"
        timeout = float(ns.profile) + max(30.0, 2.0 * float(ns.profile))
        req = Request(base + path, data=b"{}", method="POST",
                      headers={"Content-Type": "application/json"})
    elif ns.memory:
        path = "/memory"
    elif ns.programs:
        path = "/programs"
    elif ns.health:
        path = "/health"
    elif ns.slo:
        path = "/slo"
    else:
        path = "/metrics"
    try:
        with urlopen(req or (base + path), timeout=timeout) as resp:
            text = resp.read().decode("utf-8", "replace")
    except (URLError, OSError) as e:
        sys.stderr.write(f"mxtpu-stats: --fleet {base}{path}: {e}\n")
        return 1
    if not text.endswith("\n"):
        text += "\n"
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)
    return 0


def _load_generation_engine(name, cfg_path, max_slots=None, max_len=None,
                            paged=None, block_size=None,
                            scan_steps=None):
    """Build a :class:`serving.GenerationEngine` from a ``--gen-model``
    JSON config: architecture kwargs for ``models.gpt.GPTModel`` plus a
    ``"params"`` weights file (``Block.save_parameters`` format,
    resolved relative to the config) and optional ``"max_slots"`` /
    ``"max_len"`` engine knobs.  Omitting ``"params"`` serves random
    weights — useful for smoke tests and load drills."""
    import json

    import numpy as np

    from . import initializer as init
    from . import ndarray as nd
    from .models.gpt import GPTModel
    from .serving import GenerationEngine

    with open(cfg_path) as f:
        cfg = dict(json.load(f))
    if "vocab_size" not in cfg:
        raise SystemExit(
            f"mxtpu-serve: {cfg_path}: generation config needs at "
            'least {"vocab_size": N}')
    params = cfg.pop("params", None)
    cfg_slots = cfg.pop("max_slots", None)
    cfg_len = cfg.pop("max_len", None)
    cfg_paged = cfg.pop("paged", None)
    cfg_bs = cfg.pop("block_size", None)
    cfg_spec_k = cfg.pop("spec_k", None)    # draft configs only
    cfg_scan = cfg.pop("scan_steps", None)
    cfg_lp = cfg.pop("logprobs_topn", None)
    max_slots = cfg_slots if max_slots is None else max_slots
    max_len = cfg_len if max_len is None else max_len
    paged = cfg_paged if paged is None else paged
    block_size = cfg_bs if block_size is None else block_size
    scan_steps = cfg_scan if scan_steps is None else scan_steps
    cfg.setdefault("dropout", 0.0)      # serving never trains
    net = GPTModel(**cfg)
    net.initialize(init.Normal(0.02))
    net(nd.array(np.zeros((1, 2), np.int32)))   # settle deferred shapes
    if params is not None:
        if not os.path.isabs(params):
            params = os.path.join(os.path.dirname(
                os.path.abspath(cfg_path)), params)
        net.load_parameters(params)
    engine = GenerationEngine(net, name=name, max_slots=max_slots,
                              max_len=max_len, paged=paged,
                              block_size=block_size,
                              scan_steps=scan_steps,
                              logprobs_topn=cfg_lp)
    # surfaced by serve_main when this config backs a --gen-draft
    engine._cfg_spec_k = cfg_spec_k
    return engine


def serve_main():
    """``mxtpu-serve`` — dynamic-batching inference server over exported
    model artifacts (see docs/serving.md)::

        mxtpu-serve --model mnist=/models/mnist:7 \\
                    --model small=/models/small \\
                    [--gen-model gpt=/models/gpt.json] \\
                    [--gen-draft gpt=/models/gpt-small.json] \\
                    [--port N] [--max-batch N] [--max-delay-ms F]
                    [--queue N] [--input-names data]
                    [--input-specs 784] [--warmup] [--preload]
                    [--gen-slots N] [--gen-max-len N]
                    [--gen-paged 0|1] [--gen-block-size N]

    Each ``--model`` is ``NAME=PREFIX[:EPOCH]`` naming a
    ``HybridBlock.export`` / ``model.save_checkpoint`` pair
    (``PREFIX-symbol.json`` + ``PREFIX-EPOCH.params``).  Serves
    ``/v1/models/<name>:predict``, the model registry, ``/healthz``,
    ``/readyz`` and ``/metrics`` until SIGTERM/Ctrl-C, then drains:
    ``/readyz`` flips to 503, in-flight requests finish (within
    ``MXNET_DRAIN_SECONDS``), and the port closes cleanly — no reset
    connections.  Knobs default from ``MXNET_SERVE_*``
    (docs/env_var.md).

    Each ``--gen-model`` is ``NAME=CONFIG.json`` describing a GPT-style
    generation model: the JSON carries the architecture kwargs
    (``vocab_size``, ``units``, ``num_layers``, ...) plus ``"params"``
    — a ``Block.save_parameters`` weights file, resolved relative to
    the config — and optional ``"max_slots"``/``"max_len"`` engine
    knobs.  Generation models serve token streams at
    ``/v1/models/<NAME>:generate`` behind continuous batching
    (docs/serving.md); ``--gen-slots`` / ``--gen-max-len`` override the
    config and the ``MXNET_GEN_MAX_SLOTS`` / ``MXNET_GEN_MAX_LEN``
    env defaults.  The KV cache is paged by default (block pool +
    prefix sharing); ``--gen-paged 0`` restores the dense layout and
    ``--gen-block-size`` sets tokens per block (``MXNET_KV_PAGED`` /
    ``MXNET_KV_BLOCK_SIZE``).

    ``--gen-draft NAME=CONFIG.json`` attaches a small draft model to
    the generation model registered as ``NAME``, enabling speculative
    decoding: the draft proposes ``MXNET_SPEC_K`` tokens per step (or
    the draft config's ``"spec_k"``) and the target verifies them in
    one k+1-wide dispatch — greedy outputs stay bit-identical.
    ``--preload`` AOT-compiles every registered model's full program
    set BEFORE the port is bound, so ``/readyz`` never serves a cold
    replica."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="mxtpu-serve",
        description="serve exported models with dynamic batching over "
                    "shape-bucketed compiled engines")
    ap.add_argument("--model", action="append", default=[],
                    metavar="NAME=PREFIX[:EPOCH]",
                    help="register an exported model (repeatable)")
    ap.add_argument("--port", type=int, default=None,
                    help="HTTP port (default MXNET_SERVE_PORT or 8080; "
                         "0 picks an ephemeral port)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="rows per coalesced dispatch "
                         "(default MXNET_SERVE_MAX_BATCH or 32)")
    ap.add_argument("--max-delay-ms", type=float, default=None,
                    help="batching deadline in ms "
                         "(default MXNET_SERVE_MAX_DELAY_MS or 5)")
    ap.add_argument("--queue", type=int, default=None,
                    help="bounded queue size before backpressure "
                         "(default MXNET_SERVE_QUEUE or 128)")
    ap.add_argument("--input-names", default="data",
                    help="comma-separated graph input names "
                         "(default 'data')")
    ap.add_argument("--input-specs", default=None,
                    metavar="D1,D2[;D1,...]",
                    help="per-example input shapes, batch dim excluded — "
                         "one comma-separated shape per input, "
                         "';'-separated (e.g. '784' or '3,224,224'); "
                         "required for --warmup")
    ap.add_argument("--warmup", action="store_true",
                    help="AOT-compile every bucket before serving "
                         "(needs --input-specs; generation models warm "
                         "their prefill buckets and decode program)")
    ap.add_argument("--gen-model", action="append", default=[],
                    metavar="NAME=CONFIG.json",
                    help="register a generation model from a JSON "
                         "config (architecture kwargs + 'params' "
                         "weights path); repeatable")
    ap.add_argument("--gen-slots", type=int, default=None,
                    help="KV-cache slots per generation model (default "
                         "config or MXNET_GEN_MAX_SLOTS or 8)")
    ap.add_argument("--gen-max-len", type=int, default=None,
                    help="KV-cache sequence capacity (default config or "
                         "MXNET_GEN_MAX_LEN or the model's max_length)")
    ap.add_argument("--gen-paged", type=int, choices=(0, 1), default=None,
                    help="paged KV cache: 1 on (default; block pool + "
                         "prefix sharing), 0 dense fallback (also "
                         "MXNET_KV_PAGED)")
    ap.add_argument("--gen-block-size", type=int, default=None,
                    help="tokens per paged KV block (default "
                         "MXNET_KV_BLOCK_SIZE or 16)")
    ap.add_argument("--gen-scan-steps", type=int, default=None,
                    help="decode steps captured per scanned burst "
                         "dispatch, 0 disables the burst program "
                         "(default config or MXNET_DECODE_SCAN_STEPS "
                         "or 8)")
    ap.add_argument("--gen-draft", action="append", default=[],
                    metavar="NAME=CONFIG.json",
                    help="attach a draft model to generation model NAME "
                         "for speculative decoding (k from the config's "
                         "'spec_k' or MXNET_SPEC_K, default 4); "
                         "repeatable")
    ap.add_argument("--preload", action="store_true",
                    help="AOT-compile every model's full program set "
                         "(all buckets, decode, and the speculative "
                         "verify program) before binding the port — "
                         "/readyz never serves a cold replica")
    ns = ap.parse_args()
    if not ns.model and not ns.gen_model:
        ap.error("at least one --model NAME=PREFIX[:EPOCH] or "
                 "--gen-model NAME=CONFIG.json is required")
    input_specs = None
    if ns.input_specs is not None:
        input_specs = [tuple(int(d) for d in part.split(",") if d)
                       for part in ns.input_specs.split(";")]
    if ns.warmup and input_specs is None:
        ap.error("--warmup needs --input-specs (per-example shapes) to "
                 "synthesize bucket batches")

    from .base import getenv_int
    from .serving import InferenceEngine, ModelServer

    batcher_kw = {}
    if ns.max_batch is not None:
        batcher_kw["max_batch_size"] = ns.max_batch
    if ns.max_delay_ms is not None:
        batcher_kw["max_delay_ms"] = ns.max_delay_ms
    if ns.queue is not None:
        batcher_kw["queue_size"] = ns.queue
    srv = ModelServer(port=ns.port, host=ns.host, **batcher_kw)
    input_names = [s for s in ns.input_names.split(",") if s]
    for spec in ns.model:
        name, _, ref = spec.partition("=")
        if not name or not ref:
            ap.error(f"--model wants NAME=PREFIX[:EPOCH], got {spec!r}")
        prefix, _, epoch = ref.rpartition(":")
        if not prefix or not epoch.isdigit():
            prefix, epoch = ref, "0"
        engine = InferenceEngine.from_export(
            prefix, int(epoch), input_names=input_names,
            input_specs=input_specs,
            max_batch_size=ns.max_batch
            or getenv_int("MXNET_SERVE_MAX_BATCH", 32),
            name=name)
        srv.add_model(name, engine, warmup=ns.warmup)
        sys.stderr.write(f"mxtpu-serve: loaded {name} from {prefix} "
                         f"(epoch {int(epoch)}, buckets "
                         f"{list(engine.buckets)})\n")
    drafts = {}
    for spec in ns.gen_draft:
        name, _, cfg_path = spec.partition("=")
        if not name or not cfg_path:
            ap.error(f"--gen-draft wants NAME=CONFIG.json, got {spec!r}")
        drafts[name] = cfg_path
    gen_names = {spec.partition("=")[0] for spec in ns.gen_model}
    for name in drafts:
        if name not in gen_names:
            ap.error(f"--gen-draft {name}: no matching --gen-model")
    for spec in ns.gen_model:
        name, _, cfg_path = spec.partition("=")
        if not name or not cfg_path:
            ap.error(f"--gen-model wants NAME=CONFIG.json, got {spec!r}")
        engine = _load_generation_engine(
            name, cfg_path, max_slots=ns.gen_slots,
            max_len=ns.gen_max_len,
            paged=None if ns.gen_paged is None else bool(ns.gen_paged),
            block_size=ns.gen_block_size,
            scan_steps=ns.gen_scan_steps)
        if name in drafts:
            # the draft mirrors the target's slot/sequence geometry so
            # its cache rolls back in lock-step with the target's
            draft = _load_generation_engine(
                name + "-draft", drafts[name],
                max_slots=engine.max_slots, max_len=engine.max_len,
                paged=engine.paged,
                block_size=engine.block_size if engine.paged else None)
            engine.attach_draft(
                draft, spec_k=getattr(draft, "_cfg_spec_k", None))
            sys.stderr.write(
                f"mxtpu-serve: attached draft to {name} from "
                f"{drafts[name]} (spec_k {engine.spec_k})\n")
        srv.add_model(name, engine, warmup=ns.warmup)
        kv = (f"paged blocks={engine.num_blocks - 1}x"
              f"{engine.block_size}" if engine.paged else "dense")
        sys.stderr.write(
            f"mxtpu-serve: loaded generation model {name} from "
            f"{cfg_path} (slots {engine.max_slots}, max_len "
            f"{engine.max_len}, kv {kv}, prefill buckets "
            f"{list(engine.prefill_buckets)})\n")
    if ns.preload:
        sys.stderr.write("mxtpu-serve: preloading — compiling all "
                         "programs before binding the port...\n")
        t0 = time.time()
        srv.preload()
        sys.stderr.write(f"mxtpu-serve: preload done in "
                         f"{time.time() - t0:.1f}s\n")
    srv.start()
    sys.stderr.write(f"mxtpu-serve: listening on "
                     f"http://{ns.host}:{srv.port} "
                     f"(/v1/models, /healthz, /readyz, /metrics)\n")
    from .serving import lifecycle
    sys.exit(lifecycle.run_until_shutdown(srv))


def supervise_main():
    """``mxtpu-supervise`` — self-healing serve fleet: supervise
    ``mxtpu-serve`` replica processes behind an embedded router, with
    crash/hang detection, restart-with-backoff, flap quarantine, and
    signal-driven autoscaling (docs/robustness.md "Self-healing
    fleet")::

        mxtpu-supervise --replicas 2 --min-replicas 1 --max-replicas 4 \\
                        [--router-port N] [--compile-cache DIR]
                        [--log-dir DIR] [--no-autoscale]
                        [--autoscale-interval F]
                        -- --gen-model g=/models/gpt.json --preload

    Everything after ``--`` is passed to each ``mxtpu-serve`` replica
    verbatim (do NOT pass ``--port``/``--host`` there — the supervisor
    allocates a port per replica slot and binds replicas to
    127.0.0.1).  ``--command`` replaces the replica command wholesale
    with a shell-split template whose ``{port}`` placeholder receives
    the slot port (drills supervise arbitrary servers this way).
    Knobs default from ``MXNET_SUPERVISE_*`` / ``MXNET_AUTOSCALE_*``
    (docs/env_var.md)."""
    import argparse
    import shlex

    argv = sys.argv[1:]
    serve_args: list = []
    if "--" in argv:
        split = argv.index("--")
        argv, serve_args = argv[:split], argv[split + 1:]

    ap = argparse.ArgumentParser(
        prog="mxtpu-supervise",
        description="supervise + autoscale an mxtpu-serve fleet behind "
                    "an embedded mxtpu-router")
    ap.add_argument("--replicas", type=int, default=1,
                    help="initial fleet size (default 1; raised to "
                         "--min-replicas if smaller)")
    ap.add_argument("--min-replicas", type=int, default=None,
                    help="autoscale floor (default "
                         "MXNET_AUTOSCALE_MIN_REPLICAS or 1)")
    ap.add_argument("--max-replicas", type=int, default=None,
                    help="autoscale ceiling (default "
                         "MXNET_AUTOSCALE_MAX_REPLICAS or 4)")
    ap.add_argument("--router-port", type=int, default=0,
                    help="router listen port (default 0: ephemeral)")
    ap.add_argument("--compile-cache", metavar="DIR", default=None,
                    help="shared MXNET_COMPILE_CACHE_DIR for every "
                         "replica — scale-up cold starts reuse warm "
                         "compiled artifacts")
    ap.add_argument("--log-dir", metavar="DIR", default=None,
                    help="per-replica stdout/stderr logs land here "
                         "(default: discarded)")
    ap.add_argument("--no-autoscale", action="store_true",
                    help="supervise a fixed-size fleet (restarts and "
                         "quarantine only)")
    ap.add_argument("--autoscale-interval", type=float, default=None,
                    help="seconds between policy evaluations (default "
                         "MXNET_AUTOSCALE_INTERVAL_SECONDS or 10)")
    ap.add_argument("--command", default=None,
                    help="replica command template with a {port} "
                         "placeholder (shell-split; replaces the "
                         "default mxtpu-serve invocation)")
    ns = ap.parse_args(argv)
    if ns.command is not None and serve_args:
        ap.error("--command and '-- <mxtpu-serve args>' are exclusive")
    if ns.command is None and not serve_args:
        ap.error("replica command missing: pass '-- <mxtpu-serve args>' "
                 "or --command 'prog --port {port}'")

    from .serving import AutoscalePolicy, Supervisor, lifecycle

    if ns.command is not None:
        command = shlex.split(ns.command)
    else:
        # re-enter this interpreter's serve_main so the supervisor works
        # from a source checkout without installed console scripts
        command = [sys.executable, "-c",
                   "from incubator_mxnet_tpu._cli import serve_main; "
                   "serve_main()"] + serve_args \
            + ["--host", "127.0.0.1", "--port", "{port}"]
    child_env = {}
    if ns.compile_cache is not None:
        child_env["MXNET_COMPILE_CACHE_DIR"] = ns.compile_cache
    policy = AutoscalePolicy(min_replicas=ns.min_replicas,
                             max_replicas=ns.max_replicas)
    sup = Supervisor(command, replicas=ns.replicas, policy=policy,
                     autoscale=not ns.no_autoscale,
                     router_port=ns.router_port,
                     child_env=child_env, log_dir=ns.log_dir,
                     autoscale_interval_seconds=ns.autoscale_interval)
    sup.start()
    sys.stderr.write(
        f"mxtpu-supervise: router on http://0.0.0.0:{sup.router.port} "
        f"over {sup.alive_count()} replica(s); autoscale "
        f"{'off' if ns.no_autoscale else 'on'} "
        f"[{policy.min_replicas}, {policy.max_replicas}]\n")
    sys.exit(lifecycle.run_until_shutdown(sup))


def router_main():
    """``mxtpu-router`` — fault-tolerant front tier over a fleet of
    ``mxtpu-serve`` replicas (see docs/serving.md "Serving a fleet")::

        mxtpu-router --replica 127.0.0.1:8080 --replica 127.0.0.1:8081 \\
                     [--port N] [--retries N] [--health-interval F]
                     [--no-affinity] [--spill-margin N]
                     [--upstream-timeout F]

    Spreads ``POST /v1/models/<name>:predict`` / ``:generate`` over the
    replicas with health-aware least-loaded balancing, breaker-based
    outlier ejection, retry-with-failover (honoring ``Retry-After``),
    SSE passthrough, rendezvous-hash prefix-affine routing, and
    ``POST /admin/drain`` / ``/admin/undrain`` for zero-downtime
    rolling weight updates.  Knobs default from ``MXNET_ROUTER_*``
    (docs/env_var.md)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="mxtpu-router",
        description="route :predict/:generate over mxtpu-serve "
                    "replicas with failover, drains, and "
                    "prefix-affine balancing")
    ap.add_argument("--replica", action="append", default=[],
                    metavar="HOST:PORT",
                    help="an mxtpu-serve replica (repeatable; also "
                         "accepts a comma-separated list)")
    ap.add_argument("--port", type=int, default=None,
                    help="listen port (default MXNET_ROUTER_PORT or "
                         "8081; 0 picks an ephemeral port)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--retries", type=int, default=None,
                    help="upstream attempts beyond the first per "
                         "request (default MXNET_ROUTER_RETRIES or 2)")
    ap.add_argument("--health-interval", type=float, default=None,
                    help="seconds between /readyz+/slo polls (default "
                         "MXNET_ROUTER_HEALTH_INTERVAL_SECONDS or 0.5)")
    ap.add_argument("--no-affinity", action="store_true",
                    help="disable rendezvous-hash prefix-affine "
                         "routing for :generate")
    ap.add_argument("--spill-margin", type=int, default=None,
                    help="inflight excess over the fleet minimum at "
                         "which an affinity owner spills (default "
                         "MXNET_ROUTER_SPILL_MARGIN or 8)")
    ap.add_argument("--upstream-timeout", type=float, default=None,
                    help="per-attempt upstream timeout in seconds "
                         "(default MXNET_ROUTER_UPSTREAM_TIMEOUT_"
                         "SECONDS or 10)")
    ns = ap.parse_args()
    replicas = [r for spec in ns.replica
                for r in spec.split(",") if r.strip()]
    if not replicas:
        ap.error("at least one --replica HOST:PORT is required")

    from .serving import Router, lifecycle

    router = Router(replicas, port=ns.port, host=ns.host,
                    retries=ns.retries,
                    health_interval=ns.health_interval,
                    affinity=False if ns.no_affinity else None,
                    spill_margin=ns.spill_margin,
                    upstream_timeout=ns.upstream_timeout)
    router.start()
    sys.stderr.write(
        f"mxtpu-router: listening on http://{ns.host}:{router.port} "
        f"over {len(router.replicas)} replica(s) "
        f"({', '.join(r.id for r in router.replicas)})\n")
    sys.exit(lifecycle.run_until_shutdown(router))
