"""Autograd: tape-based reverse-mode differentiation over eager ops.

TPU-native re-design of the reference imperative autograd (reference:
src/imperative/imperative.cc Imperative::RecordOp/Backward;
python/mxnet/autograd.py).  Where the reference appends NNVM nodes to a tape
and later runs the NNVM ``Gradient`` pass, here each recorded op captures a
jax VJP closure at call time (``_TapeNode``), and ``backward`` walks the tape
in reverse topological order.  Higher-order gradients re-execute the VJP
*through the recorder* (jax can differentiate a vjp), so ``grad(create_graph
=True)`` composes — covering the reference's test_higher_order_grad.py cases.

Train/predict mode scopes mirror the reference exactly
(``record/pause/train_mode/predict_mode``).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "backward", "grad", "mark_variables",
           "get_symbol", "Function"]

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_recording(is_record: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, is_record
    return prev


def set_training(train: bool) -> bool:
    st = _st()
    prev, st.training = st.training, train
    return prev


class _RecordingStateScope:
    def __init__(self, is_record: Optional[bool], train_mode: Optional[bool]):
        self._enter_is_record = is_record
        self._enter_train_mode = train_mode
        self._prev_is_record = None
        self._prev_train_mode = None

    def __enter__(self):
        if self._enter_is_record is not None:
            self._prev_is_record = set_recording(self._enter_is_record)
        if self._enter_train_mode is not None:
            self._prev_train_mode = set_training(self._enter_train_mode)
        return self

    def __exit__(self, *exc):
        if self._enter_is_record is not None:
            set_recording(self._prev_is_record)
        if self._enter_train_mode is not None:
            set_training(self._prev_train_mode)
        return False


def record(train_mode: bool = True):
    """Scope: record ops for autograd (reference: autograd.record)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """reference: MXAutogradMarkVariables."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._require_grad = req != "null"
        v._grad_req = req
        v._grad = g
        v._ag_node = None


# ---------------------------------------------------------------------------
# tape
# ---------------------------------------------------------------------------
class _TapeNode:
    """One recorded op: primal fn + captured VJP + input arrays.

    Keeping both the primal ``fun`` and the recorded-time ``vjp_fn`` gives a
    fast first-order path (use the stored closure) and a correct higher-order
    path (re-derive the VJP through the recorder when create_graph=True) —
    the analog of the reference CachedOp "inlining" for 2nd order
    (reference: src/imperative/cached_op.cc)."""

    __slots__ = ("fun", "inputs", "vjp_fn", "out_is_tuple", "name",
                 "out_avals", "freed", "custom")

    def __init__(self, fun, inputs, vjp_fn, out_is_tuple, name,
                 custom=False):
        self.fun = fun
        self.inputs = list(inputs)
        self.vjp_fn = vjp_fn
        self.out_is_tuple = out_is_tuple
        self.name = name
        self.out_avals = []
        self.freed = False
        # custom: vjp comes from a user autograd.Function; its primal ``fun``
        # is a placeholder, so create_graph must NOT re-derive through it
        # (the stored python backward is used; grads are then first-order
        # only through this node — same limitation as the reference's
        # mx.autograd.Function).
        self.custom = custom


def _toposort(head_nodes) -> List[_TapeNode]:
    """Reverse-topological order (outputs first)."""
    order: List[_TapeNode] = []
    perm, temp = set(), set()

    def visit(n: _TapeNode):
        if id(n) in perm:
            return
        stack = [(n, iter([inp._ag_node for inp in n.inputs
                           if inp._ag_node is not None]))]
        temp.add(id(n))
        while stack:
            node, it = stack[-1]
            child = next(it, None)
            if child is None:
                stack.pop()
                temp.discard(id(node))
                if id(node) not in perm:
                    perm.add(id(node))
                    order.append(node)
            elif id(child) not in perm and id(child) not in temp:
                temp.add(id(child))
                stack.append((child, iter([inp._ag_node for inp in child.inputs
                                           if inp._ag_node is not None])))
    for n in head_nodes:
        visit(n)
    return list(reversed(order))  # heads first


def backward(heads, head_grads=None, retain_graph: bool = False,
             train_mode: bool = True, create_graph: bool = False,
             variables=None):
    """Core reverse pass (reference: Imperative::Backward).

    heads: list of NDArray to differentiate.  Gradients are accumulated into
    the ``.grad`` buffers of marked variables per their grad_req; if
    ``variables`` is given, returns grads w.r.t. those arrays instead
    (autograd.grad semantics).
    """
    from . import telemetry as _telemetry
    with _telemetry.trace_span("autograd.backward", cat="autograd"):
        return _backward_impl(heads, head_grads, retain_graph, train_mode,
                              create_graph, variables)


def _backward_impl(heads, head_grads, retain_graph, train_mode,
                   create_graph, variables):
    import jax.numpy as jnp
    from .ndarray.ndarray import NDArray, _invoke

    heads = list(heads)
    if head_grads is None:
        head_grads = [None] * len(heads)
    head_grads = list(head_grads)

    head_nodes = [h._ag_node for h in heads if h._ag_node is not None]
    if not head_nodes and not any(h._require_grad for h in heads):
        raise MXNetError(
            "cannot differentiate: outputs are not on the recorded graph "
            "(did you forget autograd.record() / attach_grad()?)")

    # pending cotangents keyed by (node id, out idx); NDArray-valued when
    # create_graph so the second-order pass can record through them.
    pending: dict = {}
    leaf_acc: dict = {}   # id(ndarray) -> cotangent
    var_ids = {id(v) for v in variables} if variables is not None else None

    for h, g in zip(heads, head_grads):
        if g is None:
            gval = NDArray(jnp.ones(h.shape, h.dtype), ctx=h.ctx)
        elif isinstance(g, NDArray):
            gval = g
        else:
            gval = NDArray(jnp.asarray(g, h.dtype), ctx=h.ctx)
        if h._ag_node is not None:
            key = (id(h._ag_node), h._ag_idx)
            pending[key] = gval if key not in pending else pending[key] + gval
        if h._require_grad or (var_ids and id(h) in var_ids):
            k = id(h)
            leaf_acc[k] = gval if k not in leaf_acc else leaf_acc[k] + gval

    order = _toposort(head_nodes)

    for node in order:
        outs = [pending.pop((id(node), i), None)
                for i in range(len(node.out_avals))]
        if all(o is None for o in outs):
            continue
        from .ndarray.sparse import BaseSparseNDArray as _SparseND
        cots = []
        for (shape, dtype), o in zip(node.out_avals, outs):
            if o is None:
                cots.append(NDArray(jnp.zeros(shape, dtype)))
            elif isinstance(o, _SparseND):
                # a sparse grad flowing through a non-sparse-aware op
                # densifies (reference: FComputeEx dense fallback)
                cots.append(o.tostype("default"))
            else:
                cots.append(o)

        if node.freed:
            raise MXNetError(
                "graph already freed: call backward(retain_graph=True) to "
                "backprop through the same graph twice")

        if create_graph and not node.custom:
            # re-derive the vjp *through the recorder*: gradient of gradient
            # sees the dependency on both primals and cotangents.
            import jax
            fun, n_in = node.fun, len(node.inputs)

            def vjp_apply(*args, _fun=fun, _n=n_in, _tup=node.out_is_tuple):
                primals, cot = args[:_n], args[_n:]
                _, vjp_fn = jax.vjp(_fun, *primals)
                gs = vjp_fn(tuple(cot) if _tup else cot[0])
                return tuple(gs) if len(gs) > 1 else gs[0]

            res = _invoke(vjp_apply, node.inputs + cots,
                          name=f"vjp[{node.name}]")
            in_grads = res if isinstance(res, list) else [res]
        else:
            cot_data = tuple(c._data for c in cots)
            gs = node.vjp_fn(cot_data if node.out_is_tuple else cot_data[0])
            # a vjp may return NDArray directly (sparse grads from the
            # Embedding sparse_grad path) — pass those through unchanged
            in_grads = [g if isinstance(g, NDArray)
                        else NDArray(g, ctx=inp.ctx)
                        for g, inp in zip(gs, node.inputs)]

        for inp, g in zip(node.inputs, in_grads):
            if inp._ag_node is not None:
                key = (id(inp._ag_node), inp._ag_idx)
                pending[key] = g if key not in pending else pending[key] + g
            if inp._require_grad or (var_ids and id(inp) in var_ids):
                k = id(inp)
                leaf_acc[k] = g if k not in leaf_acc else leaf_acc[k] + g

        if not retain_graph and not create_graph:
            node.vjp_fn = None
            node.freed = True

    # deposit into .grad buffers per grad_req
    if variables is None:
        seen = set()
        stack_arrays = []
        def collect(n):
            for inp in n.inputs:
                if id(inp) not in seen:
                    seen.add(id(inp))
                    stack_arrays.append(inp)
        for n in order:
            collect(n)
        for h in heads:
            if id(h) not in seen:
                seen.add(id(h)); stack_arrays.append(h)
        from .ndarray import sparse as _sparse
        for arr in stack_arrays:
            if arr._require_grad and id(arr) in leaf_acc:
                acc = leaf_acc[id(arr)]
                buf = arr._grad
                if isinstance(buf, _sparse.BaseSparseNDArray):
                    # sparse grad buffer (attach_grad(stype='row_sparse'))
                    if not isinstance(acc, _sparse.BaseSparseNDArray):
                        acc = acc.tostype(buf.stype)
                    if arr._grad_req == "add":
                        acc = _sparse.add(buf, acc)
                    buf._replace_with(acc)
                    continue
                if isinstance(acc, _sparse.BaseSparseNDArray):
                    acc = acc.tostype("default")
                if arr._grad_req == "add" and buf is not None:
                    buf._set_data(buf._data + acc._data)
                else:
                    if buf is None:
                        arr._grad = NDArray(acc._data, ctx=arr.ctx)
                    else:
                        buf._set_data(acc._data.astype(buf.dtype))
        return None

    out = []
    for v in variables:
        g = leaf_acc.get(id(v))
        if g is None:
            g = NDArray(jnp.zeros(v.shape, v.dtype), ctx=v.ctx)
        out.append(g)
    return out


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode: bool = True):
    """Compute gradients of heads w.r.t. variables, returning them (without
    touching ``.grad`` buffers) — reference: mx.autograd.grad.  With
    ``create_graph=True`` the returned arrays are themselves recorded, so a
    second ``backward`` gives higher-order gradients."""
    single = False
    from .ndarray.ndarray import NDArray
    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
        single = True
    if retain_graph is None:
        retain_graph = create_graph
    with _RecordingStateScope(True if create_graph else None, train_mode):
        gs = backward(heads, head_grads, retain_graph=retain_graph,
                      create_graph=create_graph, variables=variables)
    return gs[0] if single else gs


def get_symbol(x):
    raise MXNetError("get_symbol: tape-to-Symbol export is not supported; "
                     "use HybridBlock.export for deployable graphs")


class Function:
    """Custom differentiable function (reference: mx.autograd.Function,
    python/mxnet/autograd.py).  Subclass and implement forward/backward."""

    def __init__(self):
        self._saved = None

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *out_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray
        with pause():
            outputs = self.forward(*inputs)
        single_out = isinstance(outputs, NDArray)
        outs = [outputs] if single_out else list(outputs)

        if is_recording():
            fwd_self = self

            class _Node(_TapeNode):
                __slots__ = ()

            def fake_fun(*xs):  # placeholder; custom backward used instead
                return tuple(o._data for o in outs)

            node = _Node(fun=fake_fun,
                         inputs=[i for i in inputs if isinstance(i, NDArray)],
                         vjp_fn=None, out_is_tuple=not single_out,
                         name=type(self).__name__, custom=True)
            node.out_avals = [(o.shape, o.dtype) for o in outs]

            def custom_vjp(cot):
                cots = cot if isinstance(cot, tuple) else (cot,)
                with pause():
                    gs = fwd_self.backward(
                        *[NDArray(c) for c in cots])
                if isinstance(gs, NDArray):
                    gs = (gs,)
                return tuple(g._data for g in gs)

            node.vjp_fn = custom_vjp
            for i, o in enumerate(outs):
                o._ag_node = node
                o._ag_idx = i
        return outputs
