"""Device contexts.

TPU-native re-design of the reference ``Context`` (reference:
include/mxnet/base.h struct Context; python/mxnet/context.py).  The reference
enumerates cpu/gpu/cpu_pinned/cpu_shared devices and every NDArray/op carries
a Context; here a Context resolves to a concrete ``jax.Device`` and array
placement is done with ``jax.device_put`` — XLA/PJRT owns streams, so there is
no stream manager layer.

``tpu(i)`` is first-class.  ``gpu(i)`` is accepted for script portability and
resolves to the i-th accelerator (on this stack: the TPU); this is the
"switch your script's context line and keep going" migration story.
"""
from __future__ import annotations

import threading
import warnings
from typing import Optional

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "cpu_shared",
           "current_context", "num_gpus", "num_tpus", "current_device",
           "Device"]


class Context:
    """A device context ``(device_type, device_id)``.

    Supports use as a ``with`` scope to set the default context, mirroring
    the reference (reference: python/mxnet/context.py Context.__enter__).
    """

    # numeric codes kept identical to the reference for serialization parity
    # (reference: include/mxnet/base.h DeviceType)
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        elif isinstance(device_type, str):
            if device_type not in self.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        else:
            self.device_typeid = int(device_type)
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    # -- identity ----------------------------------------------------------
    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_typeid == other.device_typeid
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- jax resolution ----------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete ``jax.Device``.

        'tpu'/'gpu' both mean "the i-th accelerator of the live jax backend";
        'cpu'/'cpu_pinned'/'cpu_shared' mean the host CPU backend (pinned /
        shared distinctions are meaningless under PJRT unified host memory).
        """
        import jax

        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            try:
                devs = jax.local_devices(backend="cpu")
            except RuntimeError:
                # CPU backend unavailable (rare); fall back to default.
                devs = jax.local_devices()
            return devs[min(self.device_id, len(devs) - 1)]
        devs = _accelerators()
        if not devs:
            warnings.warn(
                f"no accelerator available; {self} falls back to cpu(0)",
                stacklevel=2)
            return jax.devices()[0]
        if self.device_id >= len(devs):
            raise MXNetError(
                f"{self}: only {len(devs)} accelerator device(s) present")
        return devs[self.device_id]

    # -- default-context scope --------------------------------------------
    def __enter__(self):
        self._old_ctx = getattr(Context._default, "value", None)
        Context._default.value = self
        return self

    def __exit__(self, *exc):
        Context._default.value = self._old_ctx
        return False

    # parity helper (reference Context::empty_cache is a GPU-pool op; XLA
    # owns the allocator so this is a best-effort no-op)
    def empty_cache(self):
        pass


# jax>=0.4 calls these Devices; export an alias for mxnet-2.x-style code.
Device = Context


def _accelerators():
    """This process's non-CPU jax devices (the axon PJRT TPU plugin
    reports platform 'axon'/'tpu' depending on version, so filter by
    != 'cpu').  LOCAL devices only: in a multi-process job another host's
    chips are non-addressable, and ``tpu(i)`` always means "my i-th
    chip" (reference Context semantics)."""
    import jax

    try:
        return [d for d in jax.local_devices() if d.platform != "cpu"]
    except RuntimeError:
        return []


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def cpu_shared(device_id: int = 0) -> Context:
    return Context("cpu_shared", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accepted for portability of reference scripts; resolves to the i-th
    accelerator (TPU on this stack)."""
    return Context("gpu", device_id)


def num_tpus() -> int:
    return len(_accelerators())


def num_gpus() -> int:
    """Parity with ``mx.context.num_gpus`` (reference: python/mxnet/context.py);
    counts accelerators."""
    return num_tpus()


def current_context() -> Context:
    """The default context: thread-local override, else cpu(0) — identical
    default to the reference."""
    ctx = getattr(Context._default, "value", None)
    return ctx if ctx is not None else cpu(0)


current_device = current_context
