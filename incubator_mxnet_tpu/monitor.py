"""Monitor (reference: python/mxnet/monitor.py) — periodic statistics over
executor outputs and arguments during training; the symbol-era debugging
lens (``Module.fit(monitor=...)``).

The reference hooks a stat callback into every executor op output; here
the executor exposes its arg/grad/output dicts after each forward/backward,
and the Monitor samples them on ``tic()``/``toc()`` boundaries."""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Tuple

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(arr: _np.ndarray):
    return float(_np.abs(arr).mean())


class Monitor:
    """reference: mx.monitor.Monitor(interval, stat_func, pattern, sort).

    Usage (same flow as the reference)::

        mon = Monitor(interval=10, pattern=".*weight")
        mon.install(executor)           # or Module.install_monitor(mon)
        for batch in data:
            mon.tic()
            ...forward/backward/update...
            mon.toc_print()
    """

    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self._executors: List = []
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, object]] = []

    def install(self, executor):
        """Attach an executor whose tensors are sampled (reference:
        Monitor.install via monitor_callback)."""
        self._executors.append(executor)
        return executor

    def tic(self):
        """Start sampling if this step is on the interval (reference:
        Monitor.tic)."""
        self.activated = (self.step % self.interval) == 0
        self.step += 1
        self.queue = []
        return self.activated

    def _collect(self):
        for ex in self._executors:
            sources = [("arg", getattr(ex, "arg_dict", {}) or {}),
                       ("grad", {f"{k}_grad": v for k, v in
                                 (getattr(ex, "grad_dict", {}) or
                                  {}).items() if v is not None})]
            outs = getattr(ex, "outputs", None) or []
            sources.append(("out", {f"output{i}": o
                                    for i, o in enumerate(outs)}))
            for _, tensors in sources:
                for name, arr in tensors.items():
                    if arr is None or not self.re_pattern.match(name):
                        continue
                    value = arr.asnumpy() if isinstance(arr, NDArray) \
                        else _np.asarray(arr)
                    self.queue.append(
                        (self.step, name, self.stat_func(value)))

    def toc(self):
        """Finish the sampling window; returns [(step, name, stat)]
        (reference: Monitor.toc)."""
        if not self.activated:
            return []
        self._collect()
        self.activated = False
        res = list(self.queue)
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            print(f"Batch: {step:7d} {name:30s} {stat}")
