"""Monitor (reference: python/mxnet/monitor.py) — periodic statistics over
tensors during training; the symbol-era debugging lens
(``Module.fit(monitor=...)``).

Two sources, both behind the same tic()/toc() API:

* **Executor mode** (reference flow) — ``install(executor)`` attaches an
  executor whose arg/grad/output dicts are sampled on each activated
  window, exactly like the reference's monitor_callback.
* **Bus mode** — ``install()`` with no executor subscribes the monitor to
  the telemetry event bus's ``OP_TIMED`` topic, so it observes the
  eager/gluon path too: every op dispatched inside an activated window is
  recorded as ``(step, "op:<name>", seconds)`` for names matching
  ``pattern``.  This is an ACTIVE subscription — it forces the per-op
  synchronous timed path while installed (same cost as running the
  profiler), which is the right trade for a debugging tool; call
  ``uninstall()`` when done.

The two modes compose: an executor-installed monitor that is also bus-
installed reports both tensor stats and op timings.
"""
from __future__ import annotations

import re
from typing import Callable, List, Optional, Tuple

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from . import telemetry as _telemetry

__all__ = ["Monitor"]


def _default_stat(arr: _np.ndarray):
    return float(_np.abs(arr).mean())


class Monitor:
    """reference: mx.monitor.Monitor(interval, stat_func, pattern, sort).

    Usage (same flow as the reference)::

        mon = Monitor(interval=10, pattern=".*weight")
        mon.install(executor)           # or Module.install_monitor(mon)
        for batch in data:
            mon.tic()
            ...forward/backward/update...
            mon.toc_print()

    Gluon/eager path (no executor)::

        mon = Monitor(interval=10, pattern="dot|softmax")
        mon.install()                   # subscribe to the op stream
        ...
        mon.uninstall()
    """

    def __init__(self, interval: int = 1,
                 stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self._executors: List = []
        self._bus_installed = False
        self.step = 0
        self.activated = False
        self.queue: List[Tuple[int, str, object]] = []

    def install(self, executor=None):
        """Attach a source.  With an executor: its tensors are sampled on
        toc() (reference: Monitor.install via monitor_callback).  Without:
        subscribe to the telemetry op stream (bus mode — observes the
        eager/gluon path; forces per-op sync while installed)."""
        if executor is None:
            if not self._bus_installed:
                _telemetry.OP_TIMED.subscribe(self._on_op)
                self._bus_installed = True
            return None
        self._executors.append(executor)
        return executor

    def uninstall(self):
        """Detach from the op stream and drop installed executors."""
        if self._bus_installed:
            _telemetry.OP_TIMED.unsubscribe(self._on_op)
            self._bus_installed = False
        self._executors = []

    def _on_op(self, name, seconds):
        if self.activated and self.re_pattern.match(name):
            self.queue.append((self.step, f"op:{name}", float(seconds)))

    def tic(self):
        """Start sampling if this step is on the interval (reference:
        Monitor.tic)."""
        self.activated = (self.step % self.interval) == 0
        self.step += 1
        self.queue = []
        return self.activated

    def _collect(self):
        for ex in self._executors:
            sources = [("arg", getattr(ex, "arg_dict", {}) or {}),
                       ("grad", {f"{k}_grad": v for k, v in
                                 (getattr(ex, "grad_dict", {}) or
                                  {}).items() if v is not None})]
            outs = getattr(ex, "outputs", None) or []
            sources.append(("out", {f"output{i}": o
                                    for i, o in enumerate(outs)}))
            for _, tensors in sources:
                for name, arr in tensors.items():
                    if arr is None or not self.re_pattern.match(name):
                        continue
                    value = arr.asnumpy() if isinstance(arr, NDArray) \
                        else _np.asarray(arr)
                    self.queue.append(
                        (self.step, name, self.stat_func(value)))

    def toc(self):
        """Finish the sampling window; returns [(step, name, stat)]
        (reference: Monitor.toc).  Bus-mode op records from the window are
        included ahead of the executor tensor stats."""
        if not self.activated:
            return []
        self._collect()
        self.activated = False
        res = list(self.queue)
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = []
        return res

    def toc_print(self):
        for step, name, stat in self.toc():
            print(f"Batch: {step:7d} {name:30s} {stat}")
