"""Black-box flight recorder — the ring that remembers what the
process was doing in the seconds before an incident.

Counters tell an operator *that* something tripped
(``mxtpu_serve_watchdog_restarts`` went to 1); they never say what the
process was doing at the time.  This module keeps a lock-cheap bounded
ring of recent runtime activity — FAULT events, finished root spans,
metric deltas — continuously, whether or not anyone is watching, and
automatically writes a postmortem JSON ("flight dump") the moment one
of the incident triggers fires:

===================  =======================================
trigger              FAULT event that fires it
===================  =======================================
watchdog restart     ``event="watchdog"`` (dead/hung worker)
breaker trip         ``event="breaker", kind="OPEN"``
non-finite skip      ``event="skipped_step"``
SIGTERM drain        ``event="shutdown"``
worker crash         ``event="crash"``
device OOM           ``event="oom"`` (RESOURCE_EXHAUSTED dispatch)
training anomaly     ``event="anomaly"`` (health plane, health.py)
===================  =======================================

A dump is the ring contents plus a full metrics snapshot plus whatever
the registered *providers* contribute — the ``ModelServer`` registers
one reporting per-model lifecycle states and the request ids currently
queued/in-flight, so a hung request can be found in the artifact by the
same ``x-request-id`` the client holds (docs/observability.md).

The recorder is reference-counted: ``telemetry.start()`` and
``ModelServer.start()`` each hold one reference, so serving gets
postmortems even when nobody turned full telemetry on.  Recording costs
one deque append under a tiny lock per event; dumps run on a daemon
thread (triggers can fire while arbitrary locks are held) and are
budgeted per process so a flapping breaker cannot fill a disk.

Knobs (docs/env_var.md): ``MXNET_FLIGHT_RING`` (ring size, default 512;
0 disables the recorder), ``MXNET_FLIGHT_DUMP_DIR`` (default
``<tmpdir>/mxtpu_flight``), ``MXNET_FLIGHT_MAX_DUMPS`` (auto-dump
budget per process, default 8).
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from .base import getenv, getenv_int
from . import telemetry as _telemetry

__all__ = ["FlightRecorder", "recorder", "default_ring_size",
           "default_dump_dir", "default_max_dumps"]


def default_ring_size() -> int:
    """``MXNET_FLIGHT_RING``: ring capacity in entries (0 disables)."""
    return getenv_int("MXNET_FLIGHT_RING", 512)


def default_dump_dir() -> str:
    """``MXNET_FLIGHT_DUMP_DIR``: where auto-dumps land."""
    return getenv("MXNET_FLIGHT_DUMP_DIR") \
        or os.path.join(tempfile.gettempdir(), "mxtpu_flight")


def default_max_dumps() -> int:
    """``MXNET_FLIGHT_MAX_DUMPS``: auto-dump budget per process."""
    return getenv_int("MXNET_FLIGHT_MAX_DUMPS", 8)


#: FAULT-event → dump-reason trigger matrix (see module docstring)
_TRIGGERS = {
    "watchdog": "watchdog_restart",
    "skipped_step": "nonfinite_skip",
    "shutdown": "sigterm_drain",
    "crash": "worker_crash",
    "oom": "resource_exhausted",
    "anomaly": "training_anomaly",
}


class FlightRecorder:
    """The bounded ring + dump machinery (one process-wide instance:
    :data:`recorder`)."""

    def __init__(self, size: Optional[int] = None):
        self._size = size
        self._ring: deque = deque(maxlen=size or default_ring_size() or 1)
        self._lock = threading.Lock()
        self._refs = 0
        self._providers: Dict[str, Callable[[], object]] = {}
        self._last_counters: Dict[str, float] = {}
        self._last_metrics_t = 0.0
        self._last_auto: Dict[str, float] = {}
        self._dump_seq = 0
        self._auto_dumps = 0
        self.last_dump_path: Optional[str] = None

    # -- lifecycle (refcounted: telemetry.start + ModelServer.start) ----
    def start(self) -> "FlightRecorder":
        """Attach to the FAULT and SPAN topics (idempotent per holder).
        A ring size of 0 (``MXNET_FLIGHT_RING=0``) disables recording
        entirely."""
        with self._lock:
            self._refs += 1
            if self._refs > 1:
                return self
            size = self._size if self._size is not None \
                else default_ring_size()
            if size <= 0:
                return self
            if self._ring.maxlen != size:
                self._ring = deque(self._ring, maxlen=size)
        _telemetry.FAULT.subscribe(self._on_fault, passive=True)
        _telemetry.SPAN.subscribe(self._on_span, passive=True)
        return self

    def stop(self) -> None:
        with self._lock:
            self._refs = max(0, self._refs - 1)
            if self._refs:
                return
        _telemetry.FAULT.unsubscribe(self._on_fault)
        _telemetry.SPAN.unsubscribe(self._on_span)

    @property
    def active(self) -> bool:
        return self._refs > 0

    def reset(self) -> None:
        """Drop the ring and restore the auto-dump budget (test
        hygiene; providers and subscriptions survive)."""
        with self._lock:
            self._ring.clear()
            self._last_counters = {}
            self._last_metrics_t = 0.0
            self._last_auto.clear()
            self._auto_dumps = 0
            self.last_dump_path = None

    # -- recording ------------------------------------------------------
    def _record(self, entry_type: str, **fields) -> None:
        # entry key is "type" — "kind" stays free for the fault kind
        entry = {"t": round(time.time(), 3), "type": entry_type}
        entry.update(fields)
        with self._lock:
            self._ring.append(entry)

    def _on_fault(self, site="?", event="?", kind=None, **kw) -> None:
        fields = {"site": site, "event": event}
        if kind is not None:
            fields["kind"] = kind
        for k, v in kw.items():
            if isinstance(v, (str, int, float, bool, list, tuple)) \
                    or v is None:
                fields[k] = v
        self._record("fault", **fields)
        reason = _TRIGGERS.get(event)
        if reason is None and event == "breaker" and kind == "OPEN":
            reason = "breaker_trip"
        if reason is not None:
            self._auto_dump(reason)

    def _on_span(self, span) -> None:
        # roots only (that is what the SPAN topic publishes) — the ring
        # keeps the headline, not the subtree; full trees stay on /trace
        fields = {"name": span.name, "cat": span.cat, "id": span.sid,
                  "seconds": span.seconds,
                  "children": len(span.children)}
        if span.attrs:
            fields["attrs"] = dict(span.attrs)
        self._record("span", **fields)

    def note_metrics(self, force: bool = False) -> None:
        """Fold the counter/gauge deltas since the last note into the
        ring (rate-limited to 1/s — the serving watchdog calls this on
        every sweep, so the ring carries a coarse metrics timeline)."""
        if not self.active:
            return
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_metrics_t < 1.0:
                return
            self._last_metrics_t = now
            last = self._last_counters
        try:
            flat = _telemetry.counters_flat()
        except Exception:
            return
        delta = {k: round(v - last.get(k, 0.0), 6)
                 for k, v in flat.items() if v != last.get(k, 0.0)}
        with self._lock:
            self._last_counters = flat
        if delta:
            self._record("metrics", delta=delta)

    # -- providers (extra state woven into every dump) ------------------
    def register_provider(self, name: str,
                          fn: Callable[[], object]) -> None:
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    def entries(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    # -- dumping --------------------------------------------------------
    def _auto_dump(self, reason: str) -> None:
        """Budgeted, debounced, async trigger path: a storm of breaker
        flaps costs at most one dump per second and
        ``MXNET_FLIGHT_MAX_DUMPS`` per process.  The debounce is
        per-reason: one incident often fires coupled triggers back to
        back (a watchdog restart trips the breaker in the same
        millisecond) and each deserves its artifact.  The write happens
        on a daemon thread because triggers fire from inside publish()
        — under breaker/batcher locks the providers will want."""
        now = time.monotonic()
        with self._lock:
            if not self._refs:
                return
            if self._auto_dumps >= default_max_dumps():
                return
            if now - self._last_auto.get(reason, -1e9) < 1.0:
                return
            self._last_auto[reason] = now
            self._auto_dumps += 1
        threading.Thread(target=self._dump_guarded, args=(reason,),
                         name="mxtpu-flight-dump", daemon=True).start()

    def _dump_guarded(self, reason: str) -> None:
        try:
            self.dump(reason)
        except Exception:               # the recorder must never take
            pass                        # the recorded program down

    def payload(self, reason: str) -> dict:
        """The postmortem document :meth:`dump` writes, as a dict: ring
        entries, a metrics snapshot, and every registered provider's
        state.  Served on replica ``GET /flight`` so a router can pull
        the implicated replica's view into a fleet incident bundle
        without touching the replica's disk."""
        self.note_metrics(force=True)
        payload = {
            "reason": reason,
            "time_unix": round(time.time(), 3),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "pid": os.getpid(),
            "ring": self.entries(),
        }
        try:
            payload["metrics"] = _telemetry.snapshot(include_memory=False)
        except Exception as e:
            payload["metrics"] = {"error": repr(e)}
        with self._lock:
            providers = dict(self._providers)
        for name, fn in providers.items():
            try:
                payload[name] = fn()
            except Exception as e:      # a sick provider is itself data
                payload[name] = {"error": repr(e)}
        return payload

    def dump(self, reason: str, path: Optional[str] = None) -> str:
        """Write the postmortem JSON and return its path.  ``path=None``
        picks ``<dump_dir>/flight_<pid>_<seq>_<reason>.json``."""
        payload = self.payload(reason)
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        if path is None:
            d = default_dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight_{os.getpid()}_{seq:03d}_{reason}.json")
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2, default=str)
            f.write("\n")
        os.replace(tmp, path)           # readers never see a torn dump
        self.last_dump_path = path
        return path


recorder = FlightRecorder()
